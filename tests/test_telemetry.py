"""Continuous telemetry plane (ISSUE 7): time-series metrics, Prometheus
exposition, convergence history, and SLO health.

Acceptance: ``/metrics`` on a real PS process, a real serving replica
process, and a real frontend process passes the strict Prometheus
text-format parser; a real two-process DCN run (PS child + this process's
workers with ``async.convergence.sample`` on) shows a non-empty
loss-vs-wallclock curve under ``/api/status`` ``convergence``; and a
freshness-lag SLO transitions firing -> ok when a killed replica
recovers.

Satellites covered here: the counter-registration audit (every
module-level ``*_totals`` provider either registered in
``metrics/registry.py`` or explicitly exempted, live-UI baselines driven
by the registry), k8s scrape-annotation rendering, and telemetry-plane
chaos (both endpoints stay available, valid, and monotonic while a
worker is SIGKILLed and a seeded fault schedule fires).
"""

import importlib
import json
import math
import os
import pkgutil
import signal
import socket as socket_mod
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.conf import AsyncConf, global_conf, set_global_conf
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.metrics import registry, reset_totals, slo
from asyncframework_tpu.metrics import prom
from asyncframework_tpu.metrics import timeseries as ts
from asyncframework_tpu.metrics import top
from asyncframework_tpu.metrics.live import (
    LiveStateListener,
    LiveUIServer,
    start_telemetry_from_conf,
)
from asyncframework_tpu.net import faults
from asyncframework_tpu.net.faults import (
    CONNECT_OP,
    CONNECT_REFUSED,
    CUT_MID_FRAME,
    DROP_REPLY,
    FaultSchedule,
    STALL_READ,
)
from asyncframework_tpu.net.retry import reset_breakers
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.serving import ServingFrontend
from asyncframework_tpu.serving import metrics as smetrics
from asyncframework_tpu.solvers import SolverConfig
from asyncframework_tpu.utils.clock import ManualClock

pytestmark = pytest.mark.telemetry

REPO = Path(__file__).parent.parent
CHILD = Path(__file__).parent / "ps_dcn_child.py"
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


def make_cfg(**kw):
    defaults = dict(
        num_workers=8, num_iterations=300, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.5, printer_freq=50, seed=42,
        calibration_iters=20, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    """Telemetry state is process-global (store, convergence history,
    SLO engine, sampler thread, counter families) -- no test may inherit
    or leak any of it.  A fresh conf is INSTALLED (global_conf() hands
    out throwaways otherwise, so a test's .set() would vanish)."""
    set_global_conf(AsyncConf())
    ts.stop_sampler()
    reset_totals()
    reset_breakers()
    faults.clear()
    yield
    ts.stop_sampler()
    set_global_conf(None)
    reset_totals()
    reset_breakers()
    faults.clear()


def _get(url: str, timeout: float = 3.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def _get_json(url: str, timeout: float = 3.0):
    status, body = _get(url, timeout=timeout)
    return status, json.loads(body)


# ----------------------------------------------------------- TimeSeriesStore
class TestTimeSeriesStore:
    def test_record_window_agg_and_percentiles(self):
        clk = ManualClock()
        st = ts.TimeSeriesStore(capacity=64, clock=clk)
        for v in (1.0, 2.0, 3.0, 4.0):
            clk.advance(1000)
            st.record("x", v)
        agg = st.window_agg("x", window_s=10.0)
        assert agg["count"] == 4
        assert agg["min"] == 1.0 and agg["max"] == 4.0
        assert agg["mean"] == 2.5 and agg["last"] == 4.0
        # trailing window restricts (cutoff inclusive: t >= now - w)
        agg2 = st.window_agg("x", window_s=1.5)
        assert agg2["count"] == 2 and agg2["min"] == 3.0

    def test_ring_bounded_and_evictions_counted(self):
        st = ts.TimeSeriesStore(capacity=8)
        for i in range(20):
            st.record("s", float(i))
        assert len(st.series("s")) == 8
        assert st.series("s")[0][1] == 12.0  # oldest evicted first
        assert st.evicted == 12
        assert st.samples_recorded == 20

    def test_rate_derivation_and_reset_clamp(self):
        clk = ManualClock()
        st = ts.TimeSeriesStore(capacity=64, clock=clk)
        for v in (0, 50, 100):
            st.record("ctr", float(v))
            clk.advance(1000)
        assert st.rate("ctr", window_s=60.0) == pytest.approx(50.0)
        # counter reset mid-window reads as a stall, never negative
        st.record("ctr", 0.0)
        assert st.rate("ctr", window_s=60.0) == 0.0

    def test_rate_needs_two_spanning_samples(self):
        st = ts.TimeSeriesStore(capacity=8)
        assert st.rate("nope", 10.0) is None
        st.record("one", 1.0)
        assert st.rate("one", 10.0) is None

    def test_record_flat_skips_non_numerics(self):
        st = ts.TimeSeriesStore(capacity=8)
        st.record_flat("f", {"a": 1, "b": True, "c": "x", "d": 2.5,
                             "e": None})
        assert sorted(st.names()) == ["f.a", "f.d"]

    def test_dump_summary_clear(self):
        st = ts.TimeSeriesStore(capacity=8)
        st.record("a", 1.0)
        st.record("b", 2.0)
        dump = st.dump()
        assert set(dump) == {"a", "b"}
        assert dump["a"][0][1] == 1.0
        s = st.summary()
        assert s["series"] == 2 and s["last"]["b"] == 2.0
        st.clear()
        assert st.names() == [] and st.samples_recorded == 0


# ------------------------------------------------------- ConvergenceHistory
class TestConvergenceHistory:
    def test_stride_compaction_keeps_full_span(self):
        h = ts.ConvergenceHistory(capacity=32)
        for k in range(500):
            h.add(float(k), k, loss=1.0 / (k + 1))
        assert h.offered == 500
        assert h.compactions >= 1
        assert h._stride > 1
        curves = h.curves()
        lw = curves["loss_vs_wallclock"]
        assert lw, "curve empty after compaction"
        # both the start and the end of the run survive compaction
        assert lw[0][0] == 0.0
        assert lw[-1][0] >= 400.0
        assert len(h._pts) <= h.capacity

    def test_non_finite_losses_do_not_poison_the_curve(self):
        h = ts.ConvergenceHistory()
        h.add(0.0, 0, loss=float("nan"))
        h.add(1.0, 1, loss=float("inf"))
        h.add(2.0, 2, loss=0.5)
        lw = h.curves()["loss_vs_wallclock"]
        assert lw == [[2.0, 0.5]]
        assert h.summary()["best_loss"] == 0.5

    def test_curves_thinned_to_max_points(self):
        h = ts.ConvergenceHistory(capacity=4096)
        for k in range(1000):
            h.add(float(k), k, loss=float(k))
        for curve in h.curves(max_points=50).values():
            assert len(curve) <= 50

    def test_summary_slope_and_loss_at(self):
        h = ts.ConvergenceHistory()
        for k in range(100):
            h.add(k * 100.0, k, loss=10.0 - k * 0.05)
        s = h.summary()
        assert s["first_loss"] == 10.0
        assert s["last_loss"] == pytest.approx(10.0 - 99 * 0.05)
        assert s["slope_per_s"] < 0  # converging
        la = s["loss_at"]
        assert la["100pct"] == s["last_loss"]
        assert la["25pct"] > la["50pct"] > la["100pct"]

    def test_loss_at_fractions_empty_and_slope_degenerate(self):
        assert ts.loss_at_fractions([]) == {
            "25pct": None, "50pct": None, "100pct": None}
        assert ts.loss_slope([]) is None
        assert ts.loss_slope([(0.0, 1.0)]) is None

    def test_loss_slope_two_point_fallback(self):
        # the trailing-half slice of a 2-point curve leaves one point;
        # the fallback must reach back to the FULL curve's last two, not
        # return None for a perfectly computable slope
        s = ts.loss_slope([(0.0, 2.0), (1000.0, 1.0)])
        assert s == pytest.approx(-1.0)  # -1 loss unit per second
        # 3 points: trailing half is the last 2, slope from those alone
        s = ts.loss_slope([(0.0, 9.0), (1000.0, 2.0), (2000.0, 1.0)])
        assert s == pytest.approx(-1.0)

    def test_buffer_wire_bound_order_and_merge_back(self):
        buf = ts.ConvergenceBuffer(capacity=64)
        for k in range(40):
            buf.add(k, 0.1 * k, 1.0)
        wire = buf.take_wire()
        assert len(wire) == ts.ConvergenceBuffer.MAX_WIRE
        assert wire[0][0] == 0  # FIFO
        # a terminally failed push merges its samples back, order kept
        buf.merge_back(wire)
        again = buf.take_wire()
        assert again == wire

    def test_buffer_bounded_drops_counted(self):
        buf = ts.ConvergenceBuffer(capacity=8)
        for k in range(20):
            buf.add(k, None, None)
        assert buf.dropped == 12
        assert len(buf.take_wire()) == 8

    def test_fold_trajectory(self):
        ts.fold_trajectory([(0.0, 2.0), (500.0, 1.0)])
        s = ts.convergence().summary()
        assert s["samples"] == 2 and s["last_loss"] == 1.0


# ------------------------------------------------------------- SLO engine
class TestSLORules:
    def test_grammar_full_and_defaults(self):
        rules = slo.parse_rules(
            "a: p95(serving.freshness_lag_ms) < 2000 over 15s for 2s; "
            "b: rate(ps.accepted) > 0.5"
        )
        assert rules[0].window_s == 15.0 and rules[0].for_s == 2.0
        assert rules[1].window_s == 30.0 and rules[1].for_s == 0.0
        assert rules[1].agg == "rate" and rules[1].op == ">"

    def test_grammar_rejects_garbage(self):
        with pytest.raises(ValueError, match="unparseable"):
            slo.parse_rules("what even is this")
        with pytest.raises(ValueError, match="unknown aggregate"):
            slo.parse_rules("a: p42(x) < 1")
        with pytest.raises(ValueError, match="duplicate"):
            slo.parse_rules("a: last(x) < 1; a: last(y) < 2")

    def test_unless_gate_clause_parses_and_round_trips(self):
        rules = slo.parse_rules(
            "floor: rate(ps.accepted) > 0.5 over 30s for 10s "
            "unless ps.done"
        )
        assert rules[0].unless_series == "ps.done"
        assert slo.parse_rules(rules[0].spec())[0] == rules[0]
        assert slo.parse_rules("a: last(x) < 1")[0].unless_series is None

    def test_default_conf_rule_set_parses(self):
        from asyncframework_tpu.conf import SLO_RULES

        rules = slo.parse_rules(str(global_conf().get(SLO_RULES)))
        by_name = {r.name: r for r in rules}
        assert {"serve_freshness", "predict_p99", "staleness_ms",
                "updates_floor"} <= set(by_name)
        # the updates/s floor stands down once the run is DONE
        assert by_name["updates_floor"].unless_series == "ps.done"


def _engine_on_manual_clock(rule_text):
    clk = ManualClock()
    st = ts.TimeSeriesStore(capacity=256, clock=clk)
    eng = slo.SLOEngine(slo.parse_rules(rule_text), store=st,
                        now_fn=lambda: clk.now_ms() / 1e3)
    return clk, st, eng


class TestSLOStateMachine:
    RULE = "lag: p95(x) < 100 over 10s for 3s"

    def test_burn_ok_pending_firing_recovery(self):
        clk, st, eng = _engine_on_manual_clock(self.RULE)

        def tick(v):
            clk.advance(1000)
            st.record("x", v)
            return eng.evaluate()["lag"]

        for _ in range(10):
            view = tick(50.0)
        assert view["state"] == slo.OK
        # violation shorter than the burn duration stays pending
        view = tick(500.0)
        assert view["state"] == slo.PENDING
        view = tick(500.0)
        assert view["state"] == slo.PENDING
        # ... and past it, fires, with the burn duration reported
        view = tick(500.0)
        view = tick(500.0)
        assert view["state"] == slo.FIRING
        assert view["burn_s"] >= 3.0
        assert view["fired"] == 1
        # recovery: the window must actually drain below the threshold
        for _ in range(12):
            view = tick(10.0)
        assert view["state"] == slo.OK
        assert view["recovered"] == 1
        assert view["burn_s"] == 0.0

    def test_transient_spike_never_fires(self):
        # `last` aggregate: one bad sample violates for ONE tick only --
        # shorter than the burn duration, so the rule peaks at pending
        # (a p95 window would legitimately hold a spike violated longer)
        clk, st, eng = _engine_on_manual_clock(
            "lag: last(x) < 100 over 10s for 3s"
        )

        def tick(v):
            clk.advance(1000)
            st.record("x", v)
            return eng.evaluate()["lag"]

        for _ in range(5):
            tick(50.0)
        assert tick(500.0)["state"] == slo.PENDING  # the spike
        states = [tick(50.0)["state"] for _ in range(12)]
        assert slo.FIRING not in states
        assert states[-1] == slo.OK

    def test_no_data_never_fires_but_firing_survives_silence(self):
        clk, st, eng = _engine_on_manual_clock(self.RULE)
        assert eng.evaluate()["lag"]["state"] == slo.NO_DATA
        # burn into firing
        for _ in range(6):
            clk.advance(1000)
            st.record("x", 900.0)
            eng.evaluate()
        assert eng.evaluate()["lag"]["state"] == slo.FIRING
        # the series goes silent (window drains empty): the alarm HOLDS
        clk.advance(60_000)
        assert eng.evaluate()["lag"]["state"] == slo.FIRING

    def test_unless_gate_stands_down_even_a_firing_rule(self):
        """A finished run (ps.done=1) must not leave the updates/s floor
        wedged firing: the gate clears the state, unlike silence."""
        clk, st, eng = _engine_on_manual_clock(
            "floor: rate(c) > 0.5 over 10s for 2s unless done"
        )
        for _ in range(6):  # a stalled counter: rate 0 -> burns to firing
            clk.advance(1000)
            st.record("c", 10.0)
            eng.evaluate()
        assert eng.evaluate()["floor"]["state"] == slo.FIRING
        clk.advance(1000)
        st.record("done", 1.0)
        view = eng.evaluate()["floor"]
        assert view["state"] == slo.NO_DATA
        assert view["unless"] == "done"
        assert view["burn_s"] == 0.0

    def test_health_rollup_and_reset(self):
        clk, st, eng = _engine_on_manual_clock(
            "a: last(x) < 100 over 10s; b: last(y) < 100 over 10s"
        )
        h = eng.health()
        assert h["state"] == slo.OK  # pure no_data = healthy idle
        clk.advance(1000)
        st.record("x", 500.0)
        h = eng.health()
        assert h["state"] == slo.FIRING  # for_s=0: violated = firing
        assert h["firing"] == ["a"]
        assert h["rules"]["b"]["state"] == slo.NO_DATA
        eng.reset()
        assert eng._states["a"].fired_count == 0

    def test_bench_verdicts(self):
        out = slo.bench_verdicts(
            300.0, [(0.0, 1.0), (1000.0, 0.5)])
        assert out["updates_floor"]["state"] == slo.OK
        assert out["serve_freshness"]["state"] == slo.NO_DATA
        out2 = slo.bench_verdicts(0.1, [])
        assert out2["updates_floor"]["state"] == "violated"


# -------------------------------------------------- freshness-lag SLO signal
class TestFreshnessLagSignal:
    def test_idle_lull_holds_failing_demand_grows(self):
        """The SLO input must distinguish "nobody is asking" (healthy
        replicas, a traffic lull -- lag holds at the last served value)
        from "demand is failing" (dead or all-UNHEALTHY replicas -- lag
        grows with the failing attempts), or the default serve_freshness
        rule false-fires on every low-QPS service."""
        assert smetrics.freshness_lag_ms() is None  # idle-from-birth
        smetrics.observe_predict("r:1", 2.0, 1, 40.0, 7)
        time.sleep(0.05)
        # no attempts since the success: held, not grown by wall time
        assert smetrics.freshness_lag_ms() == pytest.approx(40.0)
        # a failing RPC attempt advances the demand clock
        smetrics.observe_predict("r:1", 0.0, 0, 0.0, 0, ok=False)
        lag = smetrics.freshness_lag_ms()
        assert lag >= 40.0 + 50.0 * 0.9
        # ... as does an UNHEALTHY reject (alive-but-stale outage)
        time.sleep(0.05)
        smetrics.note_attempt()
        assert smetrics.freshness_lag_ms() >= lag + 50.0 * 0.9
        # recovery: next success re-anchors to the served lag
        smetrics.observe_predict("r:1", 2.0, 1, 41.0, 8)
        assert smetrics.freshness_lag_ms() == pytest.approx(41.0)


# ------------------------------------------------------ Prometheus exposition
class TestPromExposition:
    def test_render_passes_strict_parser_with_labels(self):
        smetrics.observe_predict("r:1", 2.5, 1, 40.0, 7)
        ts.convergence().add(100.0, 3, loss=0.25, grad_norm=1.5)
        body = prom.render({"role": "test", "run_id": "rid1"})
        parsed = prom.parse_exposition(body)
        assert parsed, "empty exposition"
        key = ("async_process_info", (("role", "test"), ("run_id", "rid1")))
        assert parsed[key] == 1.0
        # registered counter families appear with the _total suffix
        assert any(name.startswith("async_serving_") and
                   name.endswith("_total") for (name, _l) in parsed)
        # convergence gauges
        assert any(name == "async_convergence_loss"
                   for (name, _l) in parsed)
        # SLO states for every conf rule, coded
        slo_states = {dict(l)["rule"]: v for (n, l), v in parsed.items()
                      if n == "async_slo_state"}
        assert "updates_floor" in slo_states
        assert set(slo_states.values()) <= {-1.0, 0.0, 1.0, 2.0}

    def test_metric_name_sanitization(self):
        assert prom._metric_name("async", "net_bytes", "sent.PULL",
                                 "total") == "async_net_bytes_sent_PULL_total"
        assert prom._metric_name("9bad").startswith("_")

    def test_high_water_keys_are_gauges_not_counters(self):
        ps_dcn._pl_fold({"inflight_max": 3, "prefetch_hits": 5})
        body = prom.render({"role": "t"})
        assert "async_pipeline_inflight_max " in body.replace("{", " {") \
            or "async_pipeline_inflight_max{" in body
        assert "async_pipeline_inflight_max_total" not in body
        assert "async_pipeline_prefetch_hits_total" in body

    def test_render_groups_metrics_contiguously(self):
        """The exposition format requires all lines of one metric to be
        a single uninterrupted group; the SLO loop emits state/value/
        fired per RULE, so the writer must regroup per metric."""
        global_conf().set(
            "async.slo.rules",
            "a: p95(serving.freshness_lag_ms) < 2000; "
            "b: p99(serving.predict_p99_ms) < 500; "
            "c: max(ps.staleness_ms) < 1500",
        )
        slo.reset_engine()
        ts.store().record("serving.freshness_lag_ms", 10.0)
        ts.store().record("serving.predict_p99_ms", 10.0)
        body = prom.render({"role": "t"})
        seen, closed = [], set()
        for line in body.splitlines():
            name = line.split(None, 3)[2] if line.startswith("#") \
                else line.split("{")[0].split()[0]
            if seen and seen[-1] == name:
                continue
            assert name not in closed, f"{name} group interrupted"
            if seen:
                closed.add(seen[-1])
            seen.append(name)
        # and the multi-rule SLO gauges really did exercise regrouping
        states = [n for n in seen if n == "async_slo_state"]
        assert states == ["async_slo_state"]

    def test_parser_rejects_interleaved_groups(self):
        with pytest.raises(ValueError, match="interleaved"):
            prom.parse_exposition(
                "# TYPE x gauge\nx 1\n# TYPE y gauge\ny 1\nx 2\n")

    def test_parser_rejects_malformed(self):
        with pytest.raises(ValueError, match="undeclared"):
            prom.parse_exposition("orphan_sample 1.0\n")
        with pytest.raises(ValueError, match="bad TYPE"):
            prom.parse_exposition("# TYPE x flavor\nx 1\n")
        with pytest.raises(ValueError, match="bad value"):
            prom.parse_exposition("# TYPE x gauge\nx lots\n")
        with pytest.raises(ValueError, match="bad label"):
            prom.parse_exposition('# TYPE x gauge\nx{a=unquoted} 1\n')
        with pytest.raises(ValueError, match="bad comment"):
            prom.parse_exposition("# WAT x\n")

    def test_large_counters_render_full_precision(self):
        """'%g' would quantize a 10 MB byte counter to 6 significant
        digits, corrupting scrape-side rate() deltas."""
        big = 10_485_763
        ps_dcn._pl_fold({"prefetch_hits": big})
        body = prom.render({"role": "t"})
        parsed = prom.parse_exposition(body)
        vals = [v for (n, _l), v in parsed.items()
                if n == "async_pipeline_prefetch_hits_total"]
        assert vals == [float(big)]
        assert str(big) in body  # printed exact, not 1.04858e+07

    def test_label_escaping_round_trips(self):
        body = prom.render({"role": 'we"ird\\label', "run_id": "r"})
        parsed = prom.parse_exposition(body)
        assert parsed  # strict parse survived the escaped labels


# --------------------------------------------- registry + audit (satellite)
#: providers that legitimately live OUTSIDE the registry, with the reason
AUDIT_EXEMPT = {
    # the registry's own aggregate view (the consumer, not a producer)
    ("asyncframework_tpu.metrics.registry", "all_totals"),
    # aggregated INTO the registered `net` family by net_totals()
    ("asyncframework_tpu.net.retry", "retry_totals"),
}


def _walk_totals_providers():
    """Every public module-level ``*_totals`` callable in the package
    (the audit surface).  Import failures are skipped -- a module the
    suite cannot import cannot leak counters into this process either."""
    import asyncframework_tpu

    providers = {}
    for info in pkgutil.walk_packages(asyncframework_tpu.__path__,
                                      prefix="asyncframework_tpu."):
        if ".native" in info.name:
            continue
        try:
            mod = importlib.import_module(info.name)
        except Exception:
            continue
        for attr in dir(mod):
            if (attr.startswith("_") or attr.startswith("reset")
                    or not attr.endswith("_totals")):
                continue
            fn = getattr(mod, attr)
            if callable(fn):
                providers[(info.name, attr)] = fn
    return providers


class TestRegistryAudit:
    def test_every_totals_provider_is_registered_or_exempt(self):
        """THE audit (satellite 1): a counter family added anywhere in the
        package without a registry entry -- the bug class where a second
        run inherits counts because reset/baseline enumerations forgot it
        -- fails this test by name."""
        registered = set()
        for fam in registry.families().values():
            registered.add(id(fam._resolve(fam.totals_attr)))
        exempt_ids = set()
        for (mod_name, attr) in AUDIT_EXEMPT:
            exempt_ids.add(id(getattr(importlib.import_module(mod_name),
                                      attr)))
        strays = [
            site for site, fn in _walk_totals_providers().items()
            if id(fn) not in registered and id(fn) not in exempt_ids
        ]
        assert not strays, (
            f"unregistered *_totals providers {strays}: add a "
            f"CounterFamily to metrics/registry.py (wires reset_totals, "
            f"live-UI baselines, the sampler, and /metrics at once) or "
            f"an explicit AUDIT_EXEMPT entry with a reason"
        )

    def test_families_are_flat_numeric_and_reset_zeroes(self):
        ps_dcn._pl_fold({"prefetch_hits": 5, "inflight_max": 2})
        smetrics.bump("predicts", 3)
        for name, fam in registry.families().items():
            tot = fam.totals()
            assert isinstance(tot, dict), name
            for k, v in tot.items():
                assert isinstance(k, str), (name, k)
                assert isinstance(v, (int, float)), (name, k, v)
        registry.reset_all()
        for name, fam in registry.families().items():
            assert all(v == 0 for v in fam.totals().values()), (
                f"family {name!r} not zeroed by reset_all"
            )

    def test_live_ui_baselines_cover_every_baseline_family(self):
        """Satellite 1b: the dashboard's per-run delta baselines are
        registry-driven, so a new family cannot be forgotten there."""
        listener = LiveStateListener(2)
        want = {n for n, f in registry.families().items() if f.baseline}
        assert set(listener._bases) == want

    def test_reset_totals_resets_whole_telemetry_plane(self):
        ts.store().record("x", 1.0)
        ts.convergence().add(0.0, 0, loss=1.0)
        eng_before = slo.engine()
        reset_totals()
        assert ts.store().names() == []
        assert ts.convergence().summary()["samples"] == 0
        assert slo.engine() is not eng_before  # rebuilt from conf

    def test_high_water_keys_declared_exist(self):
        fam = registry.families()["pipeline"]
        assert "inflight_max" in fam.high_water


# ------------------------------------------------------- sampler + sources
class TestSampler:
    def test_sample_once_records_families_and_sources(self):
        ps_dcn._pl_fold({"prefetch_hits": 2})
        st = ts.TimeSeriesStore(capacity=32)
        ts.sample_once(st)
        names = set(st.names())
        assert "pipeline.prefetch_hits" in names
        assert "timeseries.ticks" in names

    def test_dynamic_source_register_unregister_identity(self):
        src_a = lambda: {"v": 1}  # noqa: E731
        src_b = lambda: {"v": 2}  # noqa: E731
        ts.register_source("dyn", src_a)
        ts.register_source("dyn", src_b)  # last registration wins
        ts.unregister_source("dyn", src_a)  # stale unhook: must not land
        st = ts.TimeSeriesStore(capacity=8)
        ts.sample_once(st)
        assert st.last("dyn.v") == 2.0
        ts.unregister_source("dyn", src_b)
        st2 = ts.TimeSeriesStore(capacity=8)
        ts.sample_once(st2)
        assert st2.last("dyn.v") is None

    def test_failing_family_does_not_kill_the_tick(self):
        """A counter family whose provider raises (e.g. a lazy import
        failing in a lean process) must not kill the sampler thread."""
        from asyncframework_tpu.metrics.registry import (
            _FAMILIES,
            CounterFamily,
            _register,
        )

        _register(CounterFamily("badfam", "no.such.module",
                                "x_totals", "reset_x"))
        try:
            st = ts.TimeSeriesStore(capacity=8)
            ts.sample_once(st)  # must not raise
            assert "timeseries.ticks" in st.names()
        finally:
            _FAMILIES.pop("badfam", None)

    def test_failing_source_does_not_kill_the_tick(self):
        def boom():
            raise RuntimeError("telemetry must not crash the plane")

        ts.register_source("boom", boom)
        try:
            st = ts.TimeSeriesStore(capacity=8)
            ts.sample_once(st)  # must not raise
            assert "timeseries.ticks" in st.names()
        finally:
            ts.unregister_source("boom")

    def test_interval_nonpositive_disables_sampler(self):
        global_conf().set("async.metrics.interval.s", 0)
        ts.ensure_started()
        assert not ts.sampler_running()

    def test_sampler_thread_ticks_and_stops(self):
        global_conf().set("async.metrics.interval.s", 0.02)
        ts.ensure_started()
        assert ts.sampler_running()
        deadline = time.monotonic() + 5.0
        while ts.store().last("timeseries.ticks") is None:
            assert time.monotonic() < deadline, "sampler never ticked"
            time.sleep(0.02)
        ts.stop_sampler()
        assert not ts.sampler_running()

    def test_ps_registers_ps_source_and_unhooks_on_stop(self, devices8):
        cfg = make_cfg(num_workers=2, num_iterations=10)
        ps = ps_dcn.ParameterServer(cfg, 8, 64, device=devices8[0],
                                    port=0).start()
        try:
            st = ts.TimeSeriesStore(capacity=8)
            ts.sample_once(st)
            assert st.last("ps.accepted") == 0.0
            assert st.last("ps.clock") == 0.0
        finally:
            ps.stop()
        st2 = ts.TimeSeriesStore(capacity=8)
        ts.sample_once(st2)
        assert st2.last("ps.accepted") is None  # unhooked by stop()


# -------------------------------------------------------- HTTP endpoints
class TestTelemetryEndpoints:
    def test_bare_server_status_metrics_timeseries(self):
        global_conf().set("async.metrics.interval.s", 0)  # no thread
        srv = LiveUIServer(None, port=0, role="worker",
                           labels={"wid": "3"}).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            status, snap = _get_json(f"{base}/api/status")
            assert status == 200
            assert snap["role"] == "worker"
            assert "counters" in snap and "net" in snap["counters"]
            assert "health" in snap and "convergence" in snap
            status, body = _get(f"{base}/metrics")
            assert status == 200
            parsed = prom.parse_exposition(body)
            info = [(n, dict(l)) for (n, l) in parsed
                    if n == "async_process_info"]
            assert info and info[0][1]["role"] == "worker"
            assert info[0][1]["wid"] == "3"
            status, rings = _get_json(f"{base}/api/timeseries")
            assert status == 200 and isinstance(rings, dict)
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"{base}/definitely-not-a-page")
            assert err.value.code == 404
        finally:
            srv.stop()

    def test_start_telemetry_from_conf_gating_and_port_conflict(self):
        # default -1: off
        assert start_telemetry_from_conf("worker") is None
        global_conf().set("async.metrics.port", 0)
        global_conf().set("async.metrics.interval.s", 0)
        srv = start_telemetry_from_conf("worker")
        assert srv is not None
        try:
            # a second process-alike asking for the SAME fixed port must
            # not crash the boot path (k8s env inheritance)
            global_conf().set("async.metrics.port", srv.port)
            assert start_telemetry_from_conf("worker") is None
        finally:
            srv.stop()

    def test_bad_slo_rules_degrade_health_not_500(self):
        """A typo'd async.slo.rules must surface AS the health section,
        not take down every dashboard page while training runs fine."""
        global_conf().set("async.slo.rules", "this is not a rule")
        global_conf().set("async.metrics.interval.s", 0)
        slo.reset_engine()
        srv = LiveUIServer(None, port=0, role="worker").start()
        try:
            status, snap = _get_json(
                f"http://127.0.0.1:{srv.port}/api/status")
            assert status == 200
            assert snap["health"]["state"] == "error"
            assert "unparseable" in snap["health"]["error"]
        finally:
            srv.stop()

    def test_driver_dashboard_serves_metrics_too(self):
        global_conf().set("async.metrics.interval.s", 0)
        state = LiveStateListener(2)
        srv = LiveUIServer(state, port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            _status, snap = _get_json(f"{base}/api/status")
            assert "convergence" in snap and "health" in snap
            assert "timeseries" in snap
            _status, body = _get(f"{base}/metrics")
            assert prom.parse_exposition(body)
        finally:
            srv.stop()


# ------------------------------------------------------------- async-top
class TestAsyncTop:
    def test_sparkline(self):
        assert top.sparkline([]) == ""
        assert top.sparkline([1.0, 1.0, 1.0]) == "▁▁▁"
        line = top.sparkline(list(range(100)), width=10)
        assert len(line) == 10
        assert line[0] == "▁" and line[-1] == "█"

    def test_render_status_sections(self):
        status = {
            "role": "driver", "run_id": "r1", "elapsed_s": 12.5,
            "updates_per_sec": 300.25, "accepted": 100, "dropped": 2,
            "model_version": 99,
            "health": {"state": "firing", "firing": ["lag"], "rules": {
                "lag": {"state": "firing", "value": 5000.0,
                        "threshold": 2000.0, "op": "<", "agg": "p95",
                        "series": "serving.freshness_lag_ms",
                        "window_s": 15.0, "for_s": 2.0, "burn_s": 4.2,
                        "fired": 1, "recovered": 0},
            }},
            "convergence": {
                "samples": 10, "last_loss": 0.25, "best_loss": 0.2,
                "slope_per_s": -0.01,
                "curves": {"loss_vs_wallclock": [[0, 1.0], [1, 0.5],
                                                 [2, 0.25]]},
            },
            "trace": {"stages_ms": {
                "compute": {"count": 5, "p50": 1.0, "p95": 2.0,
                            "p99": 3.0},
            }, "staleness_ms": {"count": 5, "p95": 10.0, "max": 20.0}},
            "serving": {"detail": {"qps": 1000.0, "predicts": 50,
                                   "freshness_lag_ms": 55.0,
                                   "failovers": 1,
                                   "predict_ms": {"p50": 0.5,
                                                  "p99": 2.0}}},
            "timeseries": {"series": 12, "samples": 300, "evicted": 0},
        }
        out = top.render_status(status, plain=True)
        assert "FIRING" in out
        assert "lag" in out and "burn=4.2s" in out
        assert "converging" in out
        assert "compute" in out and "2.00" in out
        assert "qps=1000.0" in out
        assert "12 series" in out
        assert any(ch in out for ch in top._SPARK)

    def test_main_once_against_live_server(self, capsys):
        global_conf().set("async.metrics.interval.s", 0)
        srv = LiveUIServer(None, port=0, role="ps").start()
        try:
            rc = top.main([f"127.0.0.1:{srv.port}", "--once", "--plain"])
        finally:
            srv.stop()
        assert rc == 0
        out = capsys.readouterr().out
        assert "role=ps" in out

    def test_main_unreachable_is_graceful(self, capsys):
        with socket_mod.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        rc = top.main([f"127.0.0.1:{dead_port}", "--once", "--plain"])
        assert rc == 0
        assert "unreachable" in capsys.readouterr().out


# -------------------------------------------------- k8s scrape (satellite)
class TestK8sScrapeWiring:
    def _pods(self, objs):
        return [(o["metadata"]["name"], o["spec"]["template"])
                for o in objs if o.get("kind") == "Deployment"]

    def test_all_daemon_pods_annotated_and_wired(self):
        from asyncframework_tpu.deploy import k8s

        objs = (k8s.render_master() + k8s.render_workers(2)
                + k8s.render_serving(2, ps="ps:7000"))
        pods = self._pods(objs)
        assert len(pods) == 4  # master, workers, frontend, replicas
        for name, tpl in pods:
            ann = tpl["metadata"].get("annotations") or {}
            assert ann.get("prometheus.io/scrape") == "true", name
            assert ann.get("prometheus.io/port") == str(k8s.METRICS_PORT)
            assert ann.get("prometheus.io/path") == "/metrics"
            c = tpl["spec"]["containers"][0]
            env = {e["name"]: e["value"] for e in c.get("env", [])}
            assert env.get("ASYNCTPU_ASYNC_METRICS_PORT") == str(
                k8s.METRICS_PORT), name
            ports = [p["containerPort"] for p in c.get("ports", [])]
            assert k8s.METRICS_PORT in ports, name

    def test_rendered_yaml_round_trips(self):
        import yaml

        from asyncframework_tpu.deploy import k8s

        text = k8s.to_yaml(k8s.render_serving(1, ps="ps:7000"))
        docs = list(yaml.safe_load_all(text))
        assert any(
            d["metadata"]["name"] == "async-serve-replicas" for d in docs
        )


# ---------------------------------------------- telemetry plane under chaos
@pytest.mark.chaos
class TestTelemetryUnderChaos:
    def test_endpoints_survive_faults_and_sigkill(self, devices8,
                                                  monkeypatch):
        """Satellite 3: poll /api/status AND /metrics continuously while
        a seeded fault schedule fires and a worker process is SIGKILLed:
        no 500s, every status is JSON-valid, every exposition passes the
        strict parser, and counter series stay monotonic."""
        monkeypatch.setenv("ASYNCTPU_ASYNC_CONVERGENCE_SAMPLE", "4")
        monkeypatch.setenv("ASYNCTPU_ASYNC_METRICS_INTERVAL_S", "0.1")
        cfg = make_cfg(num_iterations=600, printer_freq=100,
                       run_timeout_s=240.0)
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        ui = LiveUIServer(None, port=0, role="ps").start()
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        ep = f"127.0.0.1:{ps.port}"
        sched = FaultSchedule(seed=CHAOS_SEED)
        sched.add(ep, CONNECT_OP, 3, CONNECT_REFUSED)
        sched.add(ep, "PULL", 7, STALL_READ)
        sched.add(ep, "PUSH", 5, DROP_REPLY)
        sched.add(ep, "PUSH", 11, CUT_MID_FRAME)

        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update(
            PS_ROLE="worker", PS_PORT=str(ps.port), PS_WORKER_ID="1",
            PS_NUM_WORKER_PROCS="2", PS_WIDS="4,5,6,7", PS_EVAL="0",
            PS_NUM_ITER="600",
        )
        doomed = subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        statuses, expositions, bad = [], [], []
        stop_poll = threading.Event()

        def poll():
            base = f"http://127.0.0.1:{ui.port}"
            while not stop_poll.is_set():
                try:
                    code, snap = _get_json(f"{base}/api/status")
                    if code != 200:
                        bad.append(code)
                    else:
                        statuses.append(snap)
                    code, body = _get(f"{base}/metrics")
                    if code != 200:
                        bad.append(code)
                    else:
                        expositions.append(prom.parse_exposition(body))
                except urllib.error.HTTPError as e:
                    bad.append(e.code)
                except (OSError, ValueError):
                    pass  # transient connects are not the endpoint's fault
                time.sleep(0.05)

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        try:
            with faults.injected(sched):
                t_surv = threading.Thread(
                    target=lambda: ps_dcn.run_worker_process(
                        "127.0.0.1", ps.port, [0, 1, 2, 3],
                        {w: ds.shard(w) for w in range(4)}, cfg, d, n,
                        eval_wid=0, deadline_s=240.0,
                        proc_token="survivor"),
                    daemon=True,
                )
                t_surv.start()
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    with ps._lock:
                        if all(ps.pushes_by_wid.get(w, 0) >= 2
                               for w in (4, 5, 6, 7)):
                            break
                    time.sleep(0.05)
                doomed.send_signal(signal.SIGKILL)
                doomed.wait(timeout=10)
                t_surv.join(timeout=240)
                assert not t_surv.is_alive(), "survivor never finished"
                res = ps.wait_done(timeout_s=30.0)
                assert res, str(res)
        finally:
            stop_poll.set()
            poller.join(timeout=5)
            if doomed.poll() is None:
                doomed.kill()
            ps.stop()
            ui.stop()

        assert not bad, bad
        assert len(statuses) > 10
        assert len(expositions) > 10  # every one already parsed strictly
        # monotonic counter series across snapshots (process-global view)
        acc_seq = [s["counters"]["net"].get("retries_attempted", 0)
                   for s in statuses]
        assert all(a <= b for a, b in zip(acc_seq, acc_seq[1:]))
        conv_seq = [s["convergence"]["samples"] for s in statuses]
        assert all(a <= b for a, b in zip(conv_seq, conv_seq[1:]))
        # chaos fired, the piggyback delivered convergence samples, and
        # the exposition ended populated
        assert statuses[-1]["counters"]["net"]["faults_fired"] >= 1
        assert statuses[-1]["convergence"]["samples"] > 0
        fault_vals = [e[k] for e in expositions for k in e
                      if k[0] == "async_net_faults_fired_total"]
        assert fault_vals and max(fault_vals) >= 1


# --------------------------------------------- two-process acceptance
class TestAcceptance:
    def test_convergence_curve_and_prom_on_ps_replica_frontend(
            self, devices8, monkeypatch, tmp_path):
        """Acceptance: a REAL two-process DCN run (PS child process + this
        process's workers, convergence sampling on) yields a non-empty
        loss-vs-wallclock curve in the PS's /api/status ``convergence``
        section, and /metrics on the PS process, a real replica process,
        and a real frontend process all pass the strict Prometheus
        parser."""
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update(PS_ROLE="ps", PS_NUM_WORKER_PROCS="1",
                   PS_NUM_ITER="300", PS_UI="1",
                   ASYNCTPU_ASYNC_METRICS_INTERVAL_S="0.2")
        ps_proc = subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        serve_procs = []
        statuses, expositions = [], []
        stop_poll = threading.Event()
        try:
            hello = json.loads(ps_proc.stdout.readline())
            port, ui_port = hello["port"], hello["ui_port"]

            # the PS child's UI dies with the child at run end: collect
            # its /api/status + /metrics DURING the run
            def poll():
                base = f"http://127.0.0.1:{ui_port}"
                while not stop_poll.is_set():
                    try:
                        code, snap = _get_json(f"{base}/api/status")
                        if code == 200:
                            statuses.append(snap)
                        code, body = _get(f"{base}/metrics")
                        if code == 200:
                            expositions.append(
                                prom.parse_exposition(body))
                    except (OSError, ValueError):
                        pass  # child not up yet / already gone
                    time.sleep(0.1)

            poller = threading.Thread(target=poll, daemon=True)
            poller.start()

            # real serving processes wired to the live PS, each with its
            # own telemetry endpoint on an ephemeral-free port
            def free_port():
                with socket_mod.socket() as s:
                    s.bind(("127.0.0.1", 0))
                    return s.getsockname()[1]

            fe_mport, rep_mport = free_port(), free_port()
            senv = dict(os.environ)
            senv["JAX_PLATFORMS"] = "cpu"
            senv["ASYNCTPU_FORCE_CPU"] = "1"
            senv["PYTHONPATH"] = str(REPO)
            senv["ASYNCTPU_ASYNC_METRICS_INTERVAL_S"] = "0.2"
            serve_procs.append(subprocess.Popen(
                [sys.executable, "-m", "asyncframework_tpu.serving.cli",
                 "frontend", "--host", "127.0.0.1",
                 "--conf", f"async.metrics.port={fe_mport}"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=senv, cwd=str(REPO),
            ))
            serve_procs.append(subprocess.Popen(
                [sys.executable, "-m", "asyncframework_tpu.serving.cli",
                 "replica", "--ps", f"127.0.0.1:{port}",
                 "--host", "127.0.0.1",
                 "--conf", f"async.metrics.port={rep_mport}"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=senv, cwd=str(REPO),
            ))

            # this process IS the worker process: convergence sampling on
            monkeypatch.setenv("ASYNCTPU_ASYNC_CONVERGENCE_SAMPLE", "4")
            cfg = make_cfg()
            n, d = 4096, 24
            ds = ShardedDataset.generate_on_device(
                n, d, 8, devices=devices8, seed=11, noise=0.01)
            shards = {w: ds.shard(w) for w in range(8)}
            ps_dcn.run_worker_process(
                "127.0.0.1", port, list(range(8)), shards, cfg, d, n,
                eval_wid=0, deadline_s=120.0, proc_token="telem-test",
            )
            ps_proc.communicate(timeout=60)
            stop_poll.set()
            poller.join(timeout=5)

            # --- PS process: the piggybacked samples became a real
            # loss-vs-wallclock curve in /api/status `convergence`
            assert statuses, "PS /api/status never polled"
            conv_snaps = [s["convergence"] for s in statuses
                          if (s.get("convergence") or {})
                          .get("samples", 0) > 0]
            assert conv_snaps, "convergence section never saw samples"
            conv = conv_snaps[-1]
            curve = conv["curves"]["loss_vs_wallclock"]
            assert len(curve) >= 2, conv
            # losses are finite and the curve spans real wallclock
            assert all(math.isfinite(l) for (_t, l) in curve)
            assert curve[-1][0] > curve[0][0]
            # loss-vs-version too (the adaptive controller's other axis)
            assert conv["curves"]["loss_vs_version"], conv
            # /metrics on the PS parsed strictly every poll; the last
            # ones carry the folded convergence gauges
            assert expositions, "PS /metrics never polled"
            assert any(nm == "async_convergence_loss"
                       for e in expositions for (nm, _l) in e)

            # --- replica + frontend processes: /metrics parses, labeled
            for which, mport in (("frontend", fe_mport),
                                 ("replica", rep_mport)):
                deadline = time.monotonic() + 30.0
                parsed = None
                while time.monotonic() < deadline:
                    try:
                        _code, body = _get(
                            f"http://127.0.0.1:{mport}/metrics")
                        parsed = prom.parse_exposition(body)
                        break
                    except (OSError, ValueError):
                        time.sleep(0.2)
                assert parsed, f"{which} /metrics never came up"
                roles = {dict(l).get("role") for (nm, l) in parsed
                         if nm == "async_process_info"}
                assert roles == {which}, (which, roles)
        finally:
            stop_poll.set()
            for p in serve_procs:
                try:
                    p.kill()
                except OSError:
                    pass
            if ps_proc.poll() is None:
                ps_proc.kill()

    def test_freshness_slo_fires_on_kill_and_recovers(self, devices8):
        """Acceptance: the serve-freshness SLO transitions firing -> ok
        across a replica kill/recover cycle.  The frontend (this process)
        observes predicts; the replica is a REAL OS process SIGKILLed
        mid-stream and then relaunched.  Windows are shortened via conf
        so the burn/drain cycle fits a test."""
        global_conf().set(
            "async.slo.rules",
            "serve_freshness: p95(serving.freshness_lag_ms) < 500 "
            "over 3s for 0.5s",
        )
        slo.reset_engine()
        cfg = make_cfg(num_workers=2, num_iterations=10_000,
                       bucket_ratio=0.0, calibration_iters=4)
        d, n = 16, 256
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        fe = None
        rep_proc = None
        X = np.ones((4, d), np.float32)

        def spawn_replica():
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["ASYNCTPU_FORCE_CPU"] = "1"
            env["PYTHONPATH"] = str(REPO)
            env["ASYNCTPU_ASYNC_SERVE_REFRESH_INTERVAL_S"] = "0.02"
            return subprocess.Popen(
                [sys.executable, "-m",
                 "asyncframework_tpu.serving.cli", "replica",
                 "--ps", f"127.0.0.1:{ps.port}",
                 "--host", "127.0.0.1",
                 "--frontend", f"127.0.0.1:{fe.port}"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=env, cwd=str(REPO),
            )

        def pump(seconds, deadline_state=None):
            """Predict (failures tolerated) + sampler tick until either
            the duration elapses or the health state is reached; returns
            the last state seen."""
            state = None
            t_end = time.monotonic() + seconds
            while time.monotonic() < t_end:
                try:
                    fe.predict(X)
                except Exception:
                    pass  # dead replica: the lag signal must grow anyway
                ts.sample_once()
                state = slo.engine().health()["rules"][
                    "serve_freshness"]["state"]
                if deadline_state is not None and state == deadline_state:
                    return state
                time.sleep(0.1)
            return state

        try:
            fe = ServingFrontend(deadline_s=0.5).serve(port=0,
                                                       host="127.0.0.1")
            rep_proc = spawn_replica()
            deadline = time.monotonic() + 60.0
            while fe.replica_count() < 1:
                assert time.monotonic() < deadline, "replica never joined"
                time.sleep(0.1)
            # healthy traffic: the rule must settle OK (not just no_data)
            state = pump(10.0, deadline_state=slo.OK)
            assert state == slo.OK, state

            # SIGKILL the only replica: freshness lag now grows with wall
            # time (the last successful predict recedes) -> rule FIRES
            os.kill(rep_proc.pid, signal.SIGKILL)
            rep_proc.wait(timeout=10)
            state = pump(30.0, deadline_state=slo.FIRING)
            assert state == slo.FIRING, state
            view = slo.engine().health()["rules"]["serve_freshness"]
            assert view["fired"] >= 1

            # recovery: a fresh replica process joins, predicts succeed,
            # the window drains -> rule returns to OK (not wedged firing)
            rep_proc = spawn_replica()
            state = pump(40.0, deadline_state=slo.OK)
            assert state == slo.OK, state
            view = slo.engine().health()["rules"]["serve_freshness"]
            assert view["recovered"] >= 1
            assert slo.engine().health()["state"] == slo.OK
        finally:
            if fe is not None:
                fe.stop()
            if rep_proc is not None and rep_proc.poll() is None:
                rep_proc.kill()
            ps.stop()
