"""DistributedDataset: RDD-surface parity tests.

Modeled on the reference's RDD suites (transformations/actions) plus the
missing-by-design async-op coverage (SURVEY.md section 4: the fork ships no
tests for ASYNCreduce/ASYNCaggregate/ASYNCbarrier -- we do better).
"""

import threading
import time

import numpy as np
import pytest

from asyncframework_tpu.context import AsyncContext
from asyncframework_tpu.data.dataset import DistributedDataset
from asyncframework_tpu.engine.barrier import bucket_predicate
from asyncframework_tpu.engine.scheduler import JobScheduler


@pytest.fixture()
def sched():
    s = JobScheduler(num_workers=4)
    yield s
    s.shutdown()


def test_from_list_partitioning(sched):
    ds = DistributedDataset.from_list(sched, list(range(10)))
    assert ds.num_partitions == 4
    assert ds.collect() == list(range(10))
    assert ds.count() == 10


def test_map_filter_compose(sched):
    ds = DistributedDataset.from_list(sched, list(range(20)))
    out = ds.map(lambda x: x * x).filter(lambda x: x % 2 == 0).collect()
    assert out == [x * x for x in range(20) if (x * x) % 2 == 0]


def test_reduce_and_aggregate(sched):
    ds = DistributedDataset.from_list(sched, list(range(1, 101)))
    assert ds.reduce(lambda a, b: a + b) == 5050
    total = ds.aggregate(
        (0, 0),
        lambda acc, x: (acc[0] + x, acc[1] + 1),
        lambda a, b: (a[0] + b[0], a[1] + b[1]),
    )
    assert total == (5050, 100)


def test_reduce_skips_empty_partitions(sched):
    ds = DistributedDataset.from_partitions(
        sched, {0: [3], 1: [], 2: [4], 3: []}
    )
    assert ds.reduce(lambda a, b: a + b) == 7


def test_reduce_empty_raises(sched):
    ds = DistributedDataset.from_partitions(sched, {0: [], 1: []})
    with pytest.raises(ValueError):
        ds.reduce(lambda a, b: a + b)


def test_tree_aggregate_matches_aggregate(sched):
    data = list(np.random.default_rng(0).normal(size=50))
    ds = DistributedDataset.from_list(sched, data)
    flat = ds.aggregate(0.0, lambda a, x: a + x, lambda a, b: a + b)
    tree = ds.tree_aggregate(0.0, lambda a, x: a + x, lambda a, b: a + b, depth=3)
    assert abs(flat - tree) < 1e-9
    assert abs(flat - sum(data)) < 1e-9


def test_zip_with_index_global_contiguous(sched):
    ds = DistributedDataset.from_list(sched, ["a", "b", "c", "d", "e", "f", "g"])
    indexed = ds.zip_with_index().collect()
    assert indexed == [(c, i) for i, c in enumerate("abcdefg")]


def test_sample_deterministic_and_fractional(sched):
    ds = DistributedDataset.from_list(sched, list(range(2000)))
    s1 = ds.sample(0.3, seed=7).collect()
    s2 = ds.sample(0.3, seed=7).collect()
    s3 = ds.sample(0.3, seed=8).collect()
    assert s1 == s2  # same seed -> same sample
    assert s1 != s3  # different seed -> (overwhelmingly) different
    assert 0.2 < len(s1) / 2000 < 0.4


def test_cache_computes_once(sched):
    calls = []

    def expensive():
        calls.append(1)
        return [1, 2, 3]

    ds = DistributedDataset(sched, {0: expensive}).cache()
    assert ds.collect() == [1, 2, 3]
    assert ds.collect() == [1, 2, 3]
    assert len(calls) == 1


def test_barrier_empties_non_cohort(sched):
    ctx = AsyncContext()
    # workers 0,1 available; 2 busy; 3 unseen
    ctx.get_or_create_state(0).available = True
    ctx.get_or_create_state(1).available = True
    ctx.get_or_create_state(2).available = False
    ds = DistributedDataset.from_partitions(
        sched, {0: [0], 1: [10], 2: [20], 3: [30]}
    )
    cohort, gated = ds.barrier(ctx, lambda ws: True)
    assert cohort == [0, 1, 3]  # unseen worker 3 always selected
    assert sorted(gated.collect()) == [0, 10, 30]


def test_async_reduce_streams_and_stamps_staleness(sched):
    ctx = AsyncContext()
    ds = DistributedDataset.from_list(sched, list(range(8)))
    # First job always blocks (first_iter warm-up parity), so prime it.
    ds.count()
    waiter = ds.async_reduce(lambda a, b: a + b, ctx)
    assert waiter is not None
    got = []
    for _ in range(4):
        got.append(ctx.collect_all(timeout=5.0))
    assert sum(r.data for r in got) == sum(range(8))
    assert sorted(r.worker_id for r in got) == [0, 1, 2, 3]
    # Staleness: first-arriving result has staleness 0; each later merge sees
    # the clock advanced by earlier merges (bounded by #workers - 1).
    stalenesses = sorted(r.staleness for r in got)
    assert stalenesses[0] == 0
    assert stalenesses[-1] <= 3
    assert ctx.get_current_time() == 4  # one clock bump per merged gradient
    # all workers returned to available
    assert ctx.available_workers() == 4


def test_async_reduce_empty_cohort_skips(sched):
    ctx = AsyncContext()
    ds = DistributedDataset.from_list(sched, list(range(8)))
    ds.count()
    assert ds.async_reduce(lambda a, b: a + b, ctx, cohort=[]) is None
    assert ctx.size() == 0


def test_async_aggregate_payload_and_batchsize(sched):
    ctx = AsyncContext()
    ds = DistributedDataset.from_list(sched, list(range(12)))
    ds.count()
    # ASAGA-shaped aggregate: (list of (idx, value), running sum)
    waiter = ds.async_aggregate(
        ([], 0.0),
        lambda acc, x: (acc[0] + [(x, float(x))], acc[1] + x),
        lambda a, b: (a[0] + b[0], a[1] + b[1]),
        ctx,
    )
    assert waiter is not None
    results = [ctx.collect_all(timeout=5.0) for _ in range(4)]
    assert sum(r.batch_size for r in results) == 12
    total = sum(r.data[1] for r in results)
    assert total == sum(range(12))
    pairs = [p for r in results for p in r.data[0]]
    assert sorted(x for x, _ in pairs) == list(range(12))


def test_partition_ids_validated_against_pool(sched):
    with pytest.raises(ValueError, match="out of range"):
        DistributedDataset.from_partitions(sched, {0: [1], 7: [2]})
    with pytest.raises(ValueError, match="exceeds num_workers"):
        DistributedDataset.from_list(sched, list(range(10)), num_partitions=8)


def test_empty_dataset_actions_complete(sched):
    ds = DistributedDataset.from_partitions(sched, {})
    assert ds.collect() == []
    assert ds.count() == 0


def test_barrier_with_sparse_partition_ids(sched):
    ctx = AsyncContext()
    ctx.get_or_create_state(1).available = True
    ds = DistributedDataset.from_partitions(sched, {1: [10], 3: [30]})
    cohort, gated = ds.barrier(ctx, lambda ws: True)
    assert cohort == [1, 3]
    assert sorted(gated.collect()) == [10, 30]


def test_async_failure_releases_cohort(sched):
    ctx = AsyncContext()
    boom_count = []

    def boom():
        boom_count.append(1)
        raise RuntimeError("injected task failure")

    ds = DistributedDataset(sched, {0: (lambda: [1]), 1: boom})
    # prime first_iter with a healthy dataset so the failing job is async
    DistributedDataset.from_list(sched, [1, 2]).count()
    waiter = ds.async_reduce(lambda a, b: a + b, ctx)
    assert waiter is not None
    deadline = time.monotonic() + 10
    while waiter.failed is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert waiter.failed is not None
    assert len(boom_count) == sched.max_task_failures  # retried then aborted
    # the whole cohort is released for the next round, not leaked busy
    deadline = time.monotonic() + 5
    while ctx.available_workers() < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert ctx.available_workers() == 2


def test_aggregate_does_not_mutate_callers_zero(sched):
    ds = DistributedDataset.from_list(sched, [1, 2, 3, 4])
    zero = []
    out = ds.aggregate(
        zero,
        lambda acc, x: acc + [x],
        lambda a, b: (a.extend(b) or a),  # deliberately in-place comb_op
    )
    assert sorted(out) == [1, 2, 3, 4]
    assert zero == []  # caller's zero untouched


def test_cache_immune_to_inplace_mutation(sched):
    ds = DistributedDataset.from_list(sched, [3, 1, 2]).cache()
    assert ds.collect() == [3, 1, 2]
    ds.map_partitions(lambda xs: (xs.sort() or xs)).collect()
    assert ds.collect() == [3, 1, 2]  # cache not corrupted by the sort


def test_async_reduce_with_bucket_barrier_roundtrip(sched):
    """End-to-end round: barrier -> async_reduce -> drain, twice."""
    ctx = AsyncContext()
    ds = DistributedDataset.from_list(sched, list(range(16))).cache()
    ds.count()
    for _round in range(2):
        cohort, gated = ds.barrier(ctx, bucket_predicate(ctx, 4, 0.5))
        assert cohort, "cohort empty"
        waiter = gated.async_reduce(lambda a, b: a + b, ctx, cohort=cohort)
        assert waiter is not None
        for _ in range(len(cohort)):
            ctx.collect_all(timeout=5.0)
        assert ctx.available_workers() == 4
    assert ctx.get_current_time() == 8  # 4 merges per round, 2 rounds


class TestRDDBreadth:
    """The long tail of the RDD surface: glom/coalesce/sortBy/top/... ."""

    def test_glom_and_key_by(self, sched):
        ds = DistributedDataset.from_list(sched, list(range(8)))
        parts = ds.glom().collect()
        assert [len(p) for p in parts] == [2, 2, 2, 2]
        kv = ds.key_by(lambda x: x % 2).collect()
        assert kv[:2] == [(0, 0), (1, 1)]

    def test_coalesce_preserves_order(self, sched):
        ds = DistributedDataset.from_list(sched, list(range(10)))
        c = ds.coalesce(2)
        assert c.num_partitions == 2
        assert c.collect() == list(range(10))
        assert ds.coalesce(8) is ds  # growing is a no-op

    def test_sort_by(self, sched):
        ds = DistributedDataset.from_list(sched, [5, 2, 9, 1, 7])
        assert ds.sort_by(lambda x: x).collect() == [1, 2, 5, 7, 9]
        assert ds.sort_by(lambda x: x, ascending=False).collect() == [9, 7, 5, 2, 1]

    def test_count_by_value_and_fold(self, sched):
        ds = DistributedDataset.from_list(sched, ["a", "b", "a", "a"])
        assert ds.count_by_value() == {"a": 3, "b": 1}
        nums = DistributedDataset.from_list(sched, [1, 2, 3, 4])
        assert nums.fold(0, lambda a, b: a + b) == 10

    def test_top_and_take_ordered(self, sched):
        ds = DistributedDataset.from_list(sched, [5, 2, 9, 1, 7, 3])
        assert ds.top(3) == [9, 7, 5]
        assert ds.take_ordered(3) == [1, 2, 3]
        assert ds.top(2, key=lambda x: -x) == [1, 2]

    def test_subtract_and_intersection(self, sched):
        a = DistributedDataset.from_list(sched, [1, 2, 2, 3, 4])
        b = DistributedDataset.from_list(sched, [2, 4, 5])
        assert sorted(a.subtract(b).collect()) == [1, 3]
        assert sorted(a.intersection(b).collect()) == [2, 4]

    def test_cartesian(self, sched):
        a = DistributedDataset.from_list(sched, [1, 2])
        b = DistributedDataset.from_list(sched, ["x", "y"])
        assert sorted(a.cartesian(b).collect()) == [
            (1, "x"), (1, "y"), (2, "x"), (2, "y")
        ]

    def test_set_ops_are_lazy(self, sched):
        computed = {"n": 0}

        def make_part(vals):
            def run():
                computed["n"] += 1
                return vals
            return run

        other = DistributedDataset(
            sched, {0: make_part([2, 4]), 1: make_part([5])}
        )
        a = DistributedDataset.from_list(sched, [1, 2, 3, 4])
        diff = a.subtract(other)
        cart = a.cartesian(other)
        assert computed["n"] == 0  # defining transformations computed nothing
        assert sorted(diff.collect()) == [1, 3]
        assert computed["n"] > 0
        assert len(cart.collect()) == 4 * 3

    def test_count_approx_distinct(self, sched):
        data = [i % 500 for i in range(5000)]
        ds = DistributedDataset.from_list(sched, data)
        est = ds.count_approx_distinct(relative_sd=0.02)
        assert abs(est - 500) / 500 < 0.1

    def test_take_sample(self, sched):
        ds = DistributedDataset.from_list(sched, list(range(100)))
        s1 = ds.take_sample(False, 10, seed=1)
        assert len(s1) == 10 and len(set(s1)) == 10
        s2 = ds.take_sample(True, 150, seed=2)
        assert len(s2) == 150  # replacement allows > population
        assert ds.take_sample(False, 10, seed=1) == s1  # deterministic

    def test_count_approx_distinct_on_pairs_and_strings(self, sched):
        data = [("k%d" % (i % 40), i % 3) for i in range(1000)]
        ds = DistributedDataset.from_list(sched, data)
        est = ds.count_approx_distinct(relative_sd=0.01)
        assert abs(est - 120) <= 12  # 40 keys x 3 values
        strs = DistributedDataset.from_list(sched, ["s%d" % (i % 77) for i in range(500)])
        assert abs(strs.count_approx_distinct(0.01) - 77) <= 8

    def test_count_approx_distinct_unachievable_sd_rejected(self, sched):
        ds = DistributedDataset.from_list(sched, [1, 2, 3])
        import pytest as _pytest

        with _pytest.raises(ValueError, match="p="):
            ds.count_approx_distinct(relative_sd=0.0001)


class TestStatsAndHistogram:
    def test_stats_matches_numpy(self, sched):
        rs = np.random.default_rng(4)
        vals = rs.normal(3.0, 2.0, 500).tolist()
        ds = DistributedDataset.from_list(sched, vals)
        st = ds.stats()
        assert st.count == 500
        np.testing.assert_allclose(st.mean, np.mean(vals), rtol=1e-9)
        np.testing.assert_allclose(st.stdev, np.std(vals), rtol=1e-9)
        np.testing.assert_allclose(
            st.sample_variance, np.var(vals, ddof=1), rtol=1e-9
        )
        assert st.min == min(vals) and st.max == max(vals)
        np.testing.assert_allclose(st.sum, np.sum(vals), rtol=1e-9)

    def test_histogram_even_buckets(self, sched):
        ds = DistributedDataset.from_list(sched, [float(i) for i in range(100)])
        edges, counts = ds.histogram(4)
        np.testing.assert_allclose(edges, [0, 24.75, 49.5, 74.25, 99.0])
        assert counts == [25, 25, 25, 25]
        assert sum(counts) == 100

    def test_histogram_custom_edges_matches_numpy(self, sched):
        rs = np.random.default_rng(5)
        vals = rs.uniform(0, 10, 400)
        ds = DistributedDataset.from_list(sched, vals.tolist())
        edges = [0.0, 2.5, 5.0, 7.5, 10.0]
        counts = ds.histogram(edges)
        want, _ = np.histogram(vals, bins=edges)
        assert counts == want.tolist()

    def test_histogram_constant_and_validation(self, sched):
        ds = DistributedDataset.from_list(sched, [7.0] * 12)
        edges, counts = ds.histogram(3)
        assert counts == [12, 0, 0]
        with pytest.raises(ValueError):
            ds.histogram(0)
        with pytest.raises(ValueError):
            ds.histogram([3.0, 1.0])

    def test_histogram_max_value_never_dropped(self, sched):
        # float rounding can land the computed last edge below the true
        # max; counts must still cover every value (review regression)
        vals = [-479733.491561483, 450148.38147423544, 1.0]
        ds = DistributedDataset.from_list(sched, vals)
        _edges, counts = ds.histogram(3)
        assert sum(counts) == 3

    def test_histogram_degenerate_range(self, sched):
        ds = DistributedDataset.from_list(sched, [1e18, 1e18 + 128])
        edges, counts = ds.histogram(4)  # interior edges collapse
        assert sum(counts) == 2

    def test_histogram_rejects_nonfinite_range(self, sched):
        ds = DistributedDataset.from_list(sched, [1.0, 2.0, float("inf")])
        with pytest.raises(ValueError, match="not finite"):
            ds.histogram(3)

    def test_stats_nan_poisons_min_max(self, sched):
        st = DistributedDataset.from_list(
            sched, [1.0, float("nan"), 5.0]
        ).stats()
        assert st.count == 3
        assert st.min != st.min and st.max != st.max  # NaN, like the mean

    def test_degenerate_edges_stay_ascending(self, sched):
        ds = DistributedDataset.from_list(sched, [1e18, 1e18 + 128])
        edges, counts = ds.histogram(4)
        assert all(a < b for a, b in zip(edges, edges[1:]))
        assert sum(counts) == 2


class TestCheckpoint:
    """RDD.checkpoint parity: lineage truncation + restart survival."""

    def test_roundtrip_and_lineage_cut(self, sched, tmp_path):
        calls = {"n": 0}

        def expensive(x):
            calls["n"] += 1
            return x * 3

        ds = (DistributedDataset.from_list(sched, list(range(40)))
              .map(expensive)
              .filter(lambda x: x % 2 == 0))
        want = [x * 3 for x in range(40) if (x * 3) % 2 == 0]
        ds.checkpoint(str(tmp_path / "ck"))
        upstream_calls = calls["n"]
        assert upstream_calls >= 40  # materialization ran the chain once
        # lineage is TRUNCATED: further actions read files, never recompute
        assert ds.collect() == want
        assert ds.count() == len(want)
        assert calls["n"] == upstream_calls

    def test_device_arrays_roundtrip(self, sched, tmp_path):
        import jax.numpy as jnp

        ds = DistributedDataset.from_partitions(
            sched, {w: [jnp.arange(4) + w] for w in range(4)}
        )
        ds.checkpoint(str(tmp_path / "ck"))
        out = ds.collect()
        for w, arr in enumerate(out):
            np.testing.assert_array_equal(np.asarray(arr), np.arange(4) + w)

    def test_restart_survival(self, sched, tmp_path):
        import subprocess
        import sys

        ck = str(tmp_path / "ck")
        (DistributedDataset.from_list(sched, list(range(100)))
         .map(lambda x: x + 1)
         .checkpoint(ck))
        # a FRESH process (new scheduler, no lineage) reads it back
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "from asyncframework_tpu.data.dataset import DistributedDataset\n"
            "from asyncframework_tpu.engine.scheduler import JobScheduler\n"
            "s = JobScheduler(num_workers=4)\n"
            "ds = DistributedDataset.from_checkpoint(s, %r)\n"
            "print(sum(ds.collect()))\n"
            "s.shutdown()\n"
        ) % ("/root/repo", ck)
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120, env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
                              "HOME": "/root"},
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip().splitlines()[-1] == str(sum(range(1, 101)))

    def test_incomplete_checkpoint_rejected(self, sched, tmp_path):
        import os

        ck = tmp_path / "ck"
        os.makedirs(ck)
        (ck / "part-00000.pkl").write_bytes(b"garbage")  # no _SUCCESS
        with pytest.raises(FileNotFoundError):
            DistributedDataset.from_checkpoint(sched, str(ck))
