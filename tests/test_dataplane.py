"""Data-plane throughput overhaul (ISSUE 4): version-cached zero-copy PULL
replies, version-gated delta pulls, vectored framing, batched gradient
apply.

The correctness spine is byte-exactness: (a) for ANY sequence of model
versions, a delta-mode pull reconstructs byte-for-byte what a full-mode
pull would have shipped (XOR deltas over float32 bit patterns, CRC-gated);
(b) a retried delta pull under injected faults can never leave the worker
on a wrong basis -- worst case it degrades to a full pull; (c) the PS's
fused merge-queue apply is bit-identical to the serial
one-dispatch-per-push order.
"""

import socket
import threading

import numpy as np
import pytest

from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.net import frame, reset_net_totals, wiredelta
from asyncframework_tpu.net import faults
from asyncframework_tpu.net.faults import (
    CUT_MID_FRAME,
    DROP_REPLY,
    FaultSchedule,
)
from asyncframework_tpu.ops import steps
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.solvers import SolverConfig


def make_cfg(**kw):
    defaults = dict(
        num_workers=2, num_iterations=40, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=10, seed=42,
        calibration_iters=4, run_timeout_s=60.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture()
def delta_conf():
    """Install a process conf with delta pulls on; always restored."""
    conf = AsyncConf().set("async.pull.mode", "delta")
    set_global_conf(conf)
    try:
        yield conf
    finally:
        set_global_conf(None)


# ------------------------------------------------------------------ codec
class TestWireDeltaCodec:
    def test_roundtrip_property_any_version_sequence(self, rng):
        """For a random walk of model versions and a client whose basis
        lags by a random number of versions, delta decode reconstructs the
        full-pull bytes EXACTLY -- including denormals, infs, NaNs, and
        negative zeros (the codec works on bit patterns, not arithmetic).
        """
        d = 512
        cur = rng.normal(size=d).astype(np.float32)
        # seed some adversarial bit patterns
        cur[:8] = [0.0, -0.0, np.inf, -np.inf, np.nan, 1e-42, -1e-42, 1e38]
        history = [cur.copy()]
        for _step in range(60):
            cur = cur.copy()
            kind = rng.integers(0, 3)
            if kind == 0:      # sparse update: few coords touched
                idx = rng.choice(d, size=int(rng.integers(1, 12)),
                                 replace=False)
                cur[idx] += rng.normal(size=idx.size).astype(np.float32)
            elif kind == 1:    # dense update
                cur += rng.normal(size=d).astype(np.float32) * 0.01
            # kind == 2: version unchanged (dropped pushes tick the clock)
            history.append(cur.copy())
            basis = history[int(rng.integers(0, len(history)))]
            wenc, payload, nnz = wiredelta.encode(cur, basis)
            got = wiredelta.decode(
                wenc, payload, nnz, basis,
                wiredelta.crc(cur.tobytes()),
            )
            assert got is not None, wenc
            assert got.tobytes() == cur.tobytes(), wenc

    def test_unchanged_is_nm_and_sparse_change_is_xdelta(self):
        w = np.arange(64, dtype=np.float32)
        wenc, payload, nnz = wiredelta.encode(w, w.copy())
        assert wenc == wiredelta.NOT_MODIFIED and payload == b""
        w2 = w.copy()
        w2[3] += 1.0
        wenc, payload, nnz = wiredelta.encode(w2, w)
        assert wenc == wiredelta.XDELTA and nnz == 1 and len(payload) == 8
        # dense change: the delta would not beat the raw payload
        w3 = w + 1.0
        wenc, payload, _nnz = wiredelta.encode(w3, w)
        assert wenc == wiredelta.FULL and payload == w3.tobytes()

    def test_decode_rejects_wrong_basis_and_corruption(self, rng):
        d = 128
        a = rng.normal(size=d).astype(np.float32)
        b = a.copy()
        b[5] += 2.0
        want = wiredelta.crc(b.tobytes())
        wenc, payload, nnz = wiredelta.encode(b, a)
        assert wenc == wiredelta.XDELTA
        wrong_basis = a.copy()
        wrong_basis[70] += 1.0
        assert wiredelta.decode(wenc, payload, nnz, wrong_basis, want) is None
        corrupt = bytearray(payload)
        corrupt[-1] ^= 0xFF
        assert wiredelta.decode(wenc, bytes(corrupt), nnz, a, want) is None
        assert wiredelta.decode(wenc, payload, nnz, None, want) is None
        # NM validates against the basis CRC, O(1) via the cached value
        assert wiredelta.decode(wiredelta.NOT_MODIFIED, b"", 0, a, want,
                                basis_crc=wiredelta.crc(a.tobytes())) is None
        out = wiredelta.decode(wenc, payload, nnz, a, want)
        assert out is not None and out.tobytes() == b.tobytes()


# -------------------------------------------------------- vectored framing
class TestVectoredFraming:
    def _pair(self):
        a, b = socket.socketpair()
        a.settimeout(5.0)
        b.settimeout(5.0)
        return a, b

    def test_vectored_stream_byte_identical_to_plain(self):
        payload = b"A" * 1000 + b"B" * 333 + b"C" * 7
        hdr = {"op": "MODEL", "ts": 3}
        a, b = self._pair()
        try:
            frame.send_msg(a, dict(hdr), payload)
            plain = frame.recv_exact(b, 8 + len(b'{"op": "MODEL", "ts": 3}')
                                     + len(payload))
        finally:
            a.close()
            b.close()
        a, b = self._pair()
        try:
            frame.send_msg_vectored(
                a, dict(hdr),
                [b"A" * 1000, memoryview(b"B" * 333), b"", b"C" * 7],
            )
            vect = frame.recv_exact(b, len(plain))
        finally:
            a.close()
            b.close()
        assert vect == plain

    def test_vectored_parses_and_counts_bytes(self):
        reset_net_totals()
        a, b = self._pair()
        try:
            parts = [np.arange(4, dtype=np.float32).tobytes(), b"tail"]
            frame.send_msg_vectored(a, {"op": "XYZ"}, parts)
            hdr, payload = frame.recv_msg(b)
        finally:
            a.close()
            b.close()
        assert hdr["op"] == "XYZ"
        assert payload == b"".join(parts)
        totals = frame.bytes_totals()
        assert totals["sent.XYZ"] == totals["recv.XYZ"] > len(payload)
        assert totals["sent"] == totals["recv"] == totals["sent.XYZ"]
        # metrics.reset_totals() must cover the wire-byte counters too
        from asyncframework_tpu.metrics import reset_totals

        reset_totals()
        assert frame.bytes_totals() == {}

    def test_large_payload_roundtrip_recv_into(self):
        blob = np.random.default_rng(3).bytes(1 << 20)
        a, b = self._pair()
        got = {}

        def rx():
            got["msg"] = frame.recv_msg(b)

        t = threading.Thread(target=rx)
        t.start()
        try:
            frame.send_msg_vectored(a, {"op": "BLOB"},
                                    [blob[: 1 << 19], blob[1 << 19:]])
            t.join(timeout=10)
            assert not t.is_alive()
        finally:
            a.close()
            b.close()
        hdr, payload = got["msg"]
        assert hdr["op"] == "BLOB" and payload == blob

    def test_cut_mid_frame_fires_on_vectored_path(self):
        # TCP loopback (not socketpair): fault schedules address peers as
        # host:port, which AF_UNIX pairs do not have
        srv = socket.create_server(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        a = socket.create_connection(("127.0.0.1", port), timeout=5.0)
        b, _addr = srv.accept()
        sched = FaultSchedule().add("*", "MODEL", 1, CUT_MID_FRAME)
        try:
            with faults.injected(sched):
                with pytest.raises(ConnectionError):
                    frame.send_msg_vectored(a, {"op": "MODEL"},
                                            [b"x" * 512, b"y" * 512])
            # the peer sees a short frame + EOF, exactly like the plain
            # path's mid-frame cut
            b.settimeout(5.0)
            with pytest.raises(ConnectionError):
                frame.recv_msg(b)
        finally:
            a.close()
            b.close()
            srv.close()


# ------------------------------------------------- PULL negotiation (PS)
class TestDeltaPullProtocol:
    def _ps(self, devices, cfg=None, d=16, n=256):
        cfg = cfg or make_cfg()
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices[0],
                                    port=0).start()
        return ps, cfg, d, n

    def _push_once(self, cl, ps, wid, d, scale=1.0, sparse_coord=None):
        """One pull+push through a FULL-mode client (advances the model).
        ``sparse_coord`` pushes a one-hot gradient so only that coordinate
        of the model changes (keeps the next delta genuinely sparse)."""
        ts, w, _avg, _cal = cl.pull(wid)
        if sparse_coord is None:
            g = np.full(d, scale, np.float32)
        else:
            g = np.zeros(d, np.float32)
            g[sparse_coord] = scale
        cl.push(wid, ts, g)

    def test_unchanged_version_pull_carries_zero_payload(self, devices8,
                                                         delta_conf):
        """THE steady-state claim: an unchanged-version re-pull is a
        header-only NOT_MODIFIED -- zero model payload bytes on the wire."""
        ps, cfg, d, n = self._ps(devices8)
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="delta")
            ts1, w1, _, _ = cl.pull(0)
            assert cl.pull_wenc["full"] == 1  # no basis yet: full
            reset_net_totals()
            ts2, w2, _, _ = cl.pull(0)
            assert cl.pull_wenc["nm"] == 1
            assert ps.pull_replies["nm"] == 1
            assert w2.tobytes() == w1.tobytes()
            assert ps.pull_model_bytes == d * 4  # only the first pull paid
            # the MODEL frame itself carried zero payload bytes: frame =
            # 2 length prefixes + header line, nothing else
            sent_model = frame.bytes_totals()["sent.MODEL"]
            assert sent_model < 200, sent_model
            cl.bye()
        finally:
            ps.stop()
            reset_net_totals()

    def test_delta_pull_reconstructs_full_pull_bytes(self, devices8,
                                                     delta_conf):
        """Wire equivalence on a live PS: for a sequence of versions, the
        delta client's model == a full client's model, byte for byte."""
        ps, cfg, d, n = self._ps(devices8)
        try:
            full_cl = ps_dcn.PSClient("127.0.0.1", ps.port,
                                      pull_mode="full")
            delta_cl = ps_dcn.PSClient("127.0.0.1", ps.port,
                                       pull_mode="delta")
            rng = np.random.default_rng(5)
            for step in range(12):
                # advance the model a random number of pushes (0 = NM)
                for _ in range(int(rng.integers(0, 3))):
                    self._push_once(full_cl, ps, 0, d,
                                    scale=float(rng.normal()))
                ts_f, w_f, _, _ = full_cl.pull(0)
                ts_d, w_d, _, _ = delta_cl.pull(1)
                assert ts_f == ts_d
                assert w_f.tobytes() == w_d.tobytes(), step
            assert delta_cl.pull_wenc["nm"] + delta_cl.pull_wenc["xdelta"] > 0
            assert delta_cl.delta_fallbacks == 0
            full_cl.bye()
            delta_cl.bye()
        finally:
            ps.stop()

    def test_evicted_basis_is_served_full_not_wrong(self, devices8):
        """A basis older than the server's version cache gets a FULL
        reply (cache miss on the SERVER side -- no client fallback
        round-trip needed, and never a wrong model)."""
        conf = (AsyncConf().set("async.pull.mode", "delta")
                .set("async.pull.delta.versions", 1))
        set_global_conf(conf)
        try:
            ps, cfg, d, n = self._ps(devices8)
            try:
                cl = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="delta")
                mover = ps_dcn.PSClient("127.0.0.1", ps.port,
                                        pull_mode="full")
                cl.pull(0)
                for _ in range(3):  # basis version ages out of the cache
                    self._push_once(mover, ps, 1, d)
                ts, w, _, _ = cl.pull(0)
                ref_ts, ref_w, _, _ = mover.pull(1)
                assert w.tobytes() == ref_w.tobytes()
                assert cl.pull_wenc["full"] == 2  # initial + cache miss
                assert cl.delta_fallbacks == 0
                cl.bye()
                mover.bye()
            finally:
                ps.stop()
        finally:
            set_global_conf(None)

    def test_cache_disabled_still_answers_nm_on_exact_version(self,
                                                              devices8):
        """async.pull.delta.versions=0: no version cache, but an
        unchanged-version re-pull (have == ts) is still NOT_MODIFIED --
        the exact match needs no cache; anything older goes full."""
        conf = (AsyncConf().set("async.pull.mode", "delta")
                .set("async.pull.delta.versions", 0))
        set_global_conf(conf)
        try:
            ps, cfg, d, n = self._ps(devices8)
            try:
                cl = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="delta")
                mover = ps_dcn.PSClient("127.0.0.1", ps.port,
                                        pull_mode="full")
                w1 = cl.pull(0)[1]
                w2 = cl.pull(0)[1]
                assert cl.pull_wenc["nm"] == 1
                assert w2.tobytes() == w1.tobytes()
                self._push_once(mover, ps, 1, d, sparse_coord=3)
                w3 = cl.pull(0)[1]
                ref = mover.pull(1)[1]
                assert w3.tobytes() == ref.tobytes()
                assert cl.pull_wenc["xdelta"] == 0  # no cache: went full
                assert len(ps._w_versions) == 0
                cl.bye()
                mover.bye()
            finally:
                ps.stop()
        finally:
            set_global_conf(None)

    def test_full_mode_deployment_never_builds_version_cache(self,
                                                            devices8):
        """No delta client -> the PS must not spend RAM/cycles on the
        version cache (it is built lazily on the first `have`)."""
        ps, cfg, d, n = self._ps(devices8)
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            for _ in range(3):
                cl.pull(0)
            assert len(ps._w_versions) == 0
            cl.bye()
        finally:
            ps.stop()

    def test_corrupt_client_basis_falls_back_to_full_pull(self, devices8,
                                                          delta_conf):
        """A client whose cached basis disagrees with what the server
        thinks it has (bit rot, basis from a different PS life) FAILS the
        CRC check and transparently re-pulls full -- never decodes a
        wrong model."""
        ps, cfg, d, n = self._ps(devices8)
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="delta")
            mover = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            cl.pull(0)
            # second pull carries `have`: the (lazy) server version cache
            # starts tracking, with the basis version in it
            cl.pull(0)
            # tamper the basis: same ts, different bytes + stale crc
            ts0, arr, crc0 = cl._basis[0]
            bad = arr.copy()
            bad[0] += 42.0
            cl._basis[0] = (ts0, bad, crc0)
            # a one-hot push keeps the model change sparse, so the server
            # answers XDELTA -- whose CRC the tampered basis must fail
            self._push_once(mover, ps, 1, d, sparse_coord=2)
            ts, w, _, _ = cl.pull(0)
            ref_ts, ref_w, _, _ = mover.pull(1)
            assert w.tobytes() == ref_w.tobytes()
            assert cl.delta_fallbacks == 1
            cl.bye()
            mover.bye()
        finally:
            ps.stop()

    def test_retried_delta_pull_under_faults_never_wrong_basis(
        self, devices8, delta_conf
    ):
        """Seeded chaos on the MODEL stream (drop_reply + cut_mid_frame):
        the retried delta pulls must still hand the worker byte-exact
        models every time, worst case via the full-pull fallback."""
        ps, cfg, d, n = self._ps(devices8)
        ep = f"127.0.0.1:{ps.port}"
        try:
            mover = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            sched = (FaultSchedule(seed=7)
                     .add(ep, "PULL", 2, DROP_REPLY)
                     .add(ep, "PULL", 4, CUT_MID_FRAME)
                     .add(ep, "PULL", 6, DROP_REPLY))
            with faults.injected(sched) as inj:
                cl = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="delta")
                for step in range(8):
                    self._push_once(mover, ps, 1, d)
                    got = cl.pull(0)
                    assert got is not None
                    _ts, w, _, _ = got
                    ref = mover.pull(1)
                    assert ref is not None
                    # the mover pulled AFTER cl, same version (no pushes in
                    # between): byte-exact or the delta path is broken
                    assert w.tobytes() == ref[1].tobytes(), step
                assert len(inj.remaining()) == 0, "all faults must fire"
                cl.bye()
                mover.bye()
        finally:
            ps.stop()


# -------------------------------------------------- batched gradient apply
class TestBatchedApply:
    def test_merge_kernels_bit_identical_to_serial(self, rng):
        """Tier-1 guard for the fused apply: the scan kernels reproduce
        the serial apply bit for bit (ASGD and ASAGA), including masked
        (rejected/padding) slots."""
        gamma, br, n, P, d, m = 1.2, 0.3, 4096, 8, 96, 6
        G = rng.normal(size=(m, d)).astype(np.float32)
        mask = np.array([1, 0, 1, 1, 0, 1], np.float32)
        w0 = rng.normal(size=d).astype(np.float32)
        import jax.numpy as jnp

        ser = steps.make_asgd_apply(gamma, br, n, P)
        w, k = jnp.asarray(w0), jnp.asarray(np.float32(5.0))
        for j in range(m):
            if mask[j] > 0:
                w, k = ser(w, jnp.asarray(G[j]), k)
        mrg = steps.make_asgd_apply_merge(gamma, br, n, P)
        w_m, k_m = mrg(jnp.asarray(w0), jnp.asarray(G), jnp.asarray(mask),
                       jnp.asarray(np.float32(5.0)))
        assert np.asarray(w).tobytes() == np.asarray(w_m).tobytes()
        assert float(k) == float(k_m)

        ab0 = rng.normal(size=d).astype(np.float32)
        ser_s = steps.make_saga_apply(gamma, br, n, P, donate_g=False)
        w, ab = jnp.asarray(w0), jnp.asarray(ab0)
        for j in range(m):
            if mask[j] > 0:
                g = jnp.asarray(G[j])
                w, ab = ser_s(w, ab, g, g)
        mrg_s = steps.make_saga_apply_merge(gamma, br, n, P)
        w_m, ab_m = mrg_s(jnp.asarray(w0), jnp.asarray(ab0),
                          jnp.asarray(G), jnp.asarray(mask))
        assert np.asarray(w).tobytes() == np.asarray(w_m).tobytes()
        assert np.asarray(ab).tobytes() == np.asarray(ab_m).tobytes()

    def test_ps_fused_drain_matches_serial_ps(self, devices8):
        """Two PSes fed the identical push sequence -- one draining through
        the fused merge queue (a real multi-item batch), one serial --
        finish with bit-identical models and identical ledgers."""
        d, n = 16, 256
        rng = np.random.default_rng(9)
        pushes = [(w % 2, rng.normal(size=d).astype(np.float32))
                  for w in range(6)]

        def run(push_merge):
            cfg = make_cfg(num_iterations=100, push_merge=push_merge)
            ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                        port=0).start()
            try:
                for j, (wid, g) in enumerate(pushes):
                    item = ps_dcn._PendingPush(
                        wid, 0, g, None, {"op": "PUSH"}, g.nbytes, None,
                        0.0,
                    )
                    ps._merge_q.append(item)
                if push_merge > 1:
                    # one drain folds the whole queue into ONE fused apply
                    with ps._lock:
                        ps._drain_merge_locked()
                    assert ps.merge_batch_max == len(pushes)
                else:
                    with ps._lock:
                        while ps._merge_q:
                            ps._drain_merge_locked()
                return (np.asarray(ps._w).tobytes(), ps.accepted,
                        ps.dropped, ps._clock)
            finally:
                ps.stop()

        serial = run(1)
        fused = run(8)
        assert fused == serial

    def test_push_merge_zero_means_serial(self, devices8):
        """An explicit push_merge=0 clamps to the classic serial path
        (regression: a truthiness check used to fall back to the conf
        default of 8)."""
        cfg = make_cfg(push_merge=0)
        ps = ps_dcn.ParameterServer(cfg, 8, 256, device=devices8[0], port=0)
        try:
            assert ps._merge_max == 1
            assert ps._apply_merge is None
        finally:
            ps.stop()

    def test_contended_run_engages_fused_applies(self, devices8):
        """Under real contention (8 workers hammering one PS) the merge
        queue must actually coalesce -- and the run still converges."""
        cfg = make_cfg(num_workers=8, num_iterations=200, bucket_ratio=0.5,
                       calibration_iters=20)
        n, d = 2048, 16
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=3, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        try:
            shards = {w: ds.shard(w) for w in range(8)}
            ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, list(range(8)), shards, cfg, d, n,
                deadline_s=60.0,
            )
            assert ps.wait_done(timeout_s=5.0)
            assert ps.accepted == 200
            assert ps.merge_merged == ps.accepted
            assert ps.merge_batch_max >= 2, (
                "8 contending workers never produced a fused batch"
            )
        finally:
            ps.stop()
