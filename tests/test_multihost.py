"""Two-process DCN bring-up test (VERDICT item 8).

Parity: ``deploy/LocalSparkCluster.scala:36`` -- the reference proves its
cluster story by booting a real Master + Workers inside one machine and
running real jobs over real RPC.  The analog here: two OS processes on
localhost initialize ``jax.distributed`` through ``parallel/multihost.py``
(one coordinator, gRPC over the loopback DCN), fence on the host barrier,
and run a psum that must cross the process boundary to produce the right
answer.  No TPU required: the forced-CPU platform exercises the identical
code path.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

CHILD = Path(__file__).parent / "dcn_child.py"


def _require_cpu_spmd() -> None:
    """Probed-capability gate (ISSUE 13 tier-1 deflake): cross-process
    SPMD on the CPU backend is a jax-build capability, not a property of
    this repo's code -- jax 0.4.37 without gloo-capable CPU collectives
    raises "Multiprocess computations aren't implemented on the CPU
    backend".  The session-cached 2-process probe (tests/test_deploy.py,
    ISSUE 12) runs the repo's own bring-up once; on incapable rigs these
    suites SKIP with the probed reason instead of failing as a
    permanent baseline."""
    from test_deploy import cpu_spmd_capability

    reason = cpu_spmd_capability()
    if reason:
        pytest.skip(reason)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_group(script: Path, n: int, timeout: float = 240.0):
    """Boot ``n`` coordinated jax.distributed processes running ``script``
    and return their final-line JSON records."""
    port = free_port()
    procs = []
    for pid in range(n):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # child sets its own platform
        env.pop("XLA_FLAGS", None)
        env.update(
            ASYNCTPU_COORDINATOR=f"127.0.0.1:{port}",
            ASYNCTPU_NUM_PROCESSES=str(n),
            ASYNCTPU_PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, str(script)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        ))
    results = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"child failed:\nstdout={out}\nstderr={err}"
        results.append(json.loads(out.strip().splitlines()[-1]))
    return results


def _check_bringup(results, n: int):
    by_pid = {r["pid"]: r for r in results}
    assert set(by_pid) == set(range(n))
    for r in results:
        assert r["active"] is True          # multi-process mode detected
        assert r["pc"] == n                 # every process joined
        assert r["devices"] == 2 * n        # n hosts x 2 virtual devices
        assert r["local_devices"] == 2
        # each device contributes (pid+1): total = 2 * sum(pid+1) = n(n+1)
        assert r["psum"] == float(n * (n + 1))
        assert r["mesh_size"] == 2 * n      # global mesh spans all hosts


def test_two_process_bringup_barrier_and_psum():
    _require_cpu_spmd()
    _check_bringup(_spawn_group(CHILD, 2, timeout=150), 2)


@pytest.mark.slow
def test_four_process_bringup_barrier_and_psum():
    """VERDICT r4 #7: the jax.distributed path past 2 processes -- four
    coordinated processes (8 global devices) join, fence, and psum across
    every process boundary (the reference's story is an 8-worker cluster,
    README.md:56)."""
    _require_cpu_spmd()
    _check_bringup(_spawn_group(CHILD, 4), 4)


def _check_training(results, n: int, single_mesh_devices: int):
    import numpy as np

    for r in results:
        assert r["active"] and r["pc"] == n and r["mesh"] == 2 * n
    # all processes computed the identical replicated model
    for r in results[1:]:
        np.testing.assert_allclose(results[0]["w"], r["w"], rtol=1e-6)

    # and it matches a single-process run on an equal-size mesh
    import dcn_train_child as child_mod  # same problem() fixture

    from asyncframework_tpu.parallel import make_mesh
    from asyncframework_tpu.solvers import MiniBatchSGD
    import jax

    X, y = child_mod.problem()
    mesh = make_mesh(single_mesh_devices,
                     devices=jax.devices()[:single_mesh_devices])
    w_local, losses, _ = MiniBatchSGD(
        gamma=0.5, batch_rate=0.5, num_iterations=40, seed=3
    ).run(X, y, mesh=mesh)
    np.testing.assert_allclose(
        results[0]["w"], np.asarray(w_local), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        results[0]["final_loss"], float(losses[-1]), rtol=1e-4
    )


def test_two_process_distributed_training_matches_local():
    """The cluster story end to end: the SAME MiniBatchSGD code trains over
    a 2-process global mesh (DCN) and produces the same model as one
    process with an equal-size mesh."""
    _require_cpu_spmd()
    results = _spawn_group(
        Path(__file__).parent / "dcn_train_child.py", 2, timeout=150
    )
    _check_training(results, 2, single_mesh_devices=4)


@pytest.mark.slow
def test_four_process_distributed_training_matches_local():
    """VERDICT r4 #7, training half: one step short of the reference's
    8-worker recipe -- 4 processes x 2 devices train over DCN and agree
    with the single-process 8-device mesh."""
    _require_cpu_spmd()
    results = _spawn_group(
        Path(__file__).parent / "dcn_train_child.py", 4
    )
    _check_training(results, 4, single_mesh_devices=8)


class TestLocalClusterLauncher:
    def test_two_process_cluster_matches_single(self):
        """LocalSparkCluster parity: the launcher's 2-process run produces
        the same recipe output as a single-process run of the same CLI."""
        import json

        _require_cpu_spmd()
        from asyncframework_tpu.cluster import launch_local_cluster

        recipe = ["--quiet", "sgd-mllib", "synthetic", "synthetic",
                  "16", "512", "4", "30", "1.0", "0", "0.5", "0.5",
                  "15", "0", "42"]
        rc, out = launch_local_cluster(
            2, recipe, devices_per_process=2, timeout_s=240.0
        )
        assert rc == 0
        summary = json.loads(
            [ln for ln in out if ln.startswith("{")][-1]
        )
        assert summary["driver"] == "sgd-mllib"
        assert summary["iterations"] == 30
        rc1, out1 = launch_local_cluster(
            1, recipe, devices_per_process=4, timeout_s=240.0
        )
        assert rc1 == 0
        s1 = json.loads([ln for ln in out1 if ln.startswith("{")][-1])
        # same global device count (2x2 vs 1x4) and same seed -- but the
        # cross-process psum reduces in a different float order, and 30
        # gamma=1.0 steps amplify the ulp-level drift; both runs must
        # converge into the same band, not match bit-for-bit
        a, b = s1["final_objective"], summary["final_objective"]
        assert a < 0.5 and b < 0.5  # both converged (initial ~ 16)
        assert abs(a - b) / max(a, b) < 0.3

    def test_usage_errors(self):
        from asyncframework_tpu.cluster import main

        assert main([]) == 2
        assert main(["notanint"]) == 2


class TestClusterASGDMode:
    def test_asgd_over_local_cluster(self):
        """VERDICT r2 item 3 end-to-end: `async-cluster 3 -- asgd ...` runs
        the DCN parameter server -- a PS process plus two worker processes,
        every gradient crossing a process boundary -- and converges."""
        import json

        from asyncframework_tpu.cluster import launch_local_cluster

        recipe = ["--quiet", "asgd", "synthetic", "synthetic",
                  "16", "4096", "8", "400", "1.0", "2147483647", "0.3",
                  "0.5", "50", "0", "42"]
        rc, out = launch_local_cluster(
            3, recipe, devices_per_process=2, timeout_s=240.0
        )
        assert rc == 0
        summary = json.loads([ln for ln in out if ln.startswith("{")][-1])
        assert summary["driver"] == "asgd-dcn-ps"
        assert summary["done"] is True
        assert summary["accepted"] == 400
        assert summary["final_objective"] is not None
        assert summary["final_objective"] < 0.05  # initial ~1.0
