"""Two-process DCN bring-up test (VERDICT item 8).

Parity: ``deploy/LocalSparkCluster.scala:36`` -- the reference proves its
cluster story by booting a real Master + Workers inside one machine and
running real jobs over real RPC.  The analog here: two OS processes on
localhost initialize ``jax.distributed`` through ``parallel/multihost.py``
(one coordinator, gRPC over the loopback DCN), fence on the host barrier,
and run a psum that must cross the process boundary to produce the right
answer.  No TPU required: the forced-CPU platform exercises the identical
code path.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

CHILD = Path(__file__).parent / "dcn_child.py"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_bringup_barrier_and_psum():
    port = free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # child sets its own platform
        env.pop("XLA_FLAGS", None)
        env.update(
            ASYNCTPU_COORDINATOR=f"127.0.0.1:{port}",
            ASYNCTPU_NUM_PROCESSES="2",
            ASYNCTPU_PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, str(CHILD)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        ))
    results = []
    for p in procs:
        out, err = p.communicate(timeout=150)
        assert p.returncode == 0, f"child failed:\nstdout={out}\nstderr={err}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {r["pid"]: r for r in results}
    assert set(by_pid) == {0, 1}
    for r in results:
        assert r["active"] is True          # multi-process mode detected
        assert r["pc"] == 2                 # both processes joined
        assert r["devices"] == 4            # 2 hosts x 2 virtual devices
        assert r["local_devices"] == 2
        assert r["psum"] == 6.0             # 2*1 + 2*2: crossed the boundary
        assert r["mesh_size"] == 4          # global mesh spans both hosts
