"""Sketches, accumulators, and the new dataset ops.

Parity: ``common/sketch`` (CountMinSketch/BloomFilter with merge),
``AccumulatorV2`` (Long/Double/Collection), and ``RDD``
flatMap/union/distinct/take/first.
"""

import threading

import numpy as np
import pytest

from asyncframework_tpu.data import DistributedDataset
from asyncframework_tpu.engine import (
    CollectionAccumulator,
    DoubleAccumulator,
    JobScheduler,
    LongAccumulator,
    MaxAccumulator,
)
from asyncframework_tpu.utils.sketch import BloomFilter, CountMinSketch


class TestCountMinSketch:
    def test_never_underestimates_and_is_close(self, rng):
        items = rng.integers(0, 200, size=20_000)
        cms = CountMinSketch(depth=5, width=1 << 12)
        cms.add(items)
        true = np.bincount(items, minlength=200)
        est = cms.estimate(np.arange(200))
        assert (est >= true).all()          # CMS invariant
        assert (est - true).mean() < 5      # tight at this width
        assert cms.total == 20_000

    def test_weighted_adds(self):
        cms = CountMinSketch()
        cms.add(np.array([7, 8]), counts=np.array([10, 3]))
        assert cms.estimate(np.array([7]))[0] >= 10

    def test_merge_equals_union(self, rng):
        a, b = CountMinSketch(seed=1), CountMinSketch(seed=1)
        xs, ys = rng.integers(0, 50, 1000), rng.integers(0, 50, 1000)
        a.add(xs)
        b.add(ys)
        both = CountMinSketch(seed=1)
        both.add(np.concatenate([xs, ys]))
        a.merge(b)
        np.testing.assert_array_equal(
            a.estimate(np.arange(50)), both.estimate(np.arange(50))
        )

    def test_merge_config_mismatch(self):
        with pytest.raises(ValueError):
            CountMinSketch(depth=3).merge(CountMinSketch(depth=5))

    def test_string_items(self):
        cms = CountMinSketch()
        cms.add(np.array(["alpha", "beta", "alpha"]))
        assert cms.estimate(np.array(["alpha"]))[0] >= 2
        assert cms.estimate(np.array(["gamma"]))[0] >= 0


class TestBloomFilter:
    def test_no_false_negatives(self, rng):
        bf = BloomFilter(capacity=5000, fpp=0.03)
        members = rng.integers(0, 10**9, 5000)
        bf.add(members)
        assert bf.might_contain(members).all()

    def test_false_positive_rate_in_band(self, rng):
        bf = BloomFilter(capacity=5000, fpp=0.03, seed=3)
        bf.add(np.arange(5000))
        probes = np.arange(10_000, 60_000)
        fpr = bf.might_contain(probes).mean()
        assert fpr < 0.06  # ~2x design headroom

    def test_merge(self):
        a, b = BloomFilter(1000, seed=2), BloomFilter(1000, seed=2)
        a.add(np.arange(0, 100))
        b.add(np.arange(100, 200))
        a.merge(b)
        assert a.might_contain(np.arange(200)).all()

    def test_float_and_string_items(self):
        bf = BloomFilter(100)
        bf.add(np.array([1.5, 2.5]))
        bf.add(np.array(["x"]))
        assert bf.might_contain(np.array([1.5]))[0]
        assert bf.might_contain(np.array(["x"]))[0]

    def test_scalar_and_object_array_items(self):
        """Scalars and mixed object arrays hash by value, not via bytes()."""
        cms = CountMinSketch()
        cms.add(5)                 # bare scalar
        cms.add("five")
        assert cms.estimate(5)[0] >= 1
        bf = BloomFilter(100)
        bf.add(np.array([10**9, -3, 2.5, "s"], dtype=object))
        assert bf.might_contain(np.array([10**9, -3], dtype=object)).all()
        with pytest.raises(TypeError):
            bf.add(np.array([object()], dtype=object))


class TestAccumulators:
    def test_long_sum_count_avg(self):
        acc = LongAccumulator("steps")
        for i in range(10):
            acc.add(i)
        assert acc.value == 45 and acc.count == 10 and acc.avg == 4.5
        acc.reset()
        assert acc.value == 0 and acc.count == 0

    def test_merge(self):
        a, b = LongAccumulator(), LongAccumulator()
        a.add(5)
        b.add(7)
        b.add(1)
        a.merge(b)
        assert a.value == 13 and a.count == 3

    def test_self_merge_does_not_deadlock(self):
        a = LongAccumulator()
        a.add(4)
        a.merge(a)  # doubles, must not hang
        assert a.value == 8
        d = DoubleAccumulator()
        d.add(1.5)
        d.merge(d)
        assert d.value == 3.0

    def test_collection_and_max(self):
        c = CollectionAccumulator()
        c.add("x")
        c.add(["y", "z"])
        assert c.value == ["x", "y", "z"]
        m = MaxAccumulator()
        m.add(3.0)
        m.add(-1.0)
        assert m.value == 3.0

    def test_thread_safety_under_concurrent_adds(self):
        acc = DoubleAccumulator()

        def worker():
            for _ in range(5000):
                acc.add(1.0)

        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert acc.value == 40_000.0

    def test_tasks_update_accumulator(self):
        """The Spark usage: tasks add, the driver reads after the job."""
        sched = JobScheduler(num_workers=4)
        acc = LongAccumulator("rows")
        try:
            ds = DistributedDataset.from_list(sched, list(range(40)))
            ds.map(lambda x: (acc.add(1), x)[1]).collect()
            assert acc.value == 40
        finally:
            sched.shutdown()


class TestDatasetOps:
    @pytest.fixture()
    def sched(self):
        s = JobScheduler(num_workers=4)
        yield s
        s.shutdown()

    def test_flat_map(self, sched):
        ds = DistributedDataset.from_list(sched, [1, 2, 3])
        assert sorted(ds.flat_map(lambda x: [x, 10 * x]).collect()) == [
            1, 2, 3, 10, 20, 30
        ]

    def test_union(self, sched):
        a = DistributedDataset.from_list(sched, [1, 2, 3, 4])
        b = DistributedDataset.from_list(sched, [5, 6])
        assert sorted(a.union(b).collect()) == [1, 2, 3, 4, 5, 6]

    def test_union_requires_same_scheduler(self, sched):
        other = JobScheduler(num_workers=4)
        try:
            a = DistributedDataset.from_list(sched, [1])
            b = DistributedDataset.from_list(other, [2])
            with pytest.raises(ValueError, match="same scheduler"):
                a.union(b)
        finally:
            other.shutdown()

    def test_distinct_keeps_first_occurrence_order(self, sched):
        ds = DistributedDataset.from_list(sched, [3, 1, 3, 2, 1, 2, 3, 3])
        out = ds.distinct().collect()
        assert sorted(out) == [1, 2, 3]
        assert len(out) == 3

    def test_take_and_first(self, sched):
        ds = DistributedDataset.from_list(sched, list(range(20)))
        assert ds.take(5) == [0, 1, 2, 3, 4]
        assert ds.take(0) == []
        assert ds.take(100) == list(range(20))
        assert ds.first() == 0

    def test_first_empty_raises(self, sched):
        ds = DistributedDataset.from_list(sched, [1]).filter(lambda x: False)
        with pytest.raises(ValueError, match="empty"):
            ds.first()


class TestHyperLogLog:
    def test_estimate_within_error(self):
        from asyncframework_tpu.utils.sketch import HyperLogLog

        h = HyperLogLog(p=12)
        n = 100_000
        h.add(np.arange(n))
        h.add(np.arange(n // 2))  # duplicates must not inflate
        est = h.estimate()
        assert abs(est - n) / n < 4 * h.relative_error

    def test_merge_equals_union(self):
        from asyncframework_tpu.utils.sketch import HyperLogLog

        a = HyperLogLog(p=12)
        b = HyperLogLog(p=12)
        a.add(np.arange(0, 60_000))
        b.add(np.arange(40_000, 100_000))
        a.merge(b)
        assert abs(a.estimate() - 100_000) / 100_000 < 4 * a.relative_error
        with pytest.raises(ValueError):
            a.merge(HyperLogLog(p=11))

    def test_small_range_linear_counting(self):
        from asyncframework_tpu.utils.sketch import HyperLogLog

        h = HyperLogLog(p=12)
        h.add(np.arange(25))
        assert abs(h.estimate() - 25) <= 2

    def test_strings_and_mixed(self):
        from asyncframework_tpu.utils.sketch import HyperLogLog

        h = HyperLogLog(p=10)
        h.add(np.asarray([f"user-{i}" for i in range(5000)], dtype=object))
        assert abs(h.estimate() - 5000) / 5000 < 4 * h.relative_error
