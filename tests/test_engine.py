"""Engine tests: scheduler modes, retries, executor loss, barrier, stragglers.

Modeled on the reference's scheduler test strategy (SURVEY.md section 4):
pure-logic tests driving the scheduler with fake task closures -- no devices,
no XLA -- plus failure-injection paths (DAGSchedulerSuite / DistributedSuite
analogs, in-process).
"""

import threading
import time

import pytest

from asyncframework_tpu.context import AsyncContext
from asyncframework_tpu.engine import (
    DelayModel,
    JobScheduler,
    build_cloud_stragglers,
    partial_barrier,
)
from asyncframework_tpu.engine.barrier import bucket_predicate
from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor
from asyncframework_tpu.engine.scheduler import ASYNC, SYNC


def collector():
    results = []
    lock = threading.Lock()

    def handler(wid, res):
        with lock:
            results.append((wid, res))

    return results, handler


class TestSchedulerModes:
    def test_sync_mode_blocks_until_all_results(self):
        sched = JobScheduler(num_workers=4)
        try:
            results, handler = collector()
            sched.set_mode(SYNC)
            waiter = sched.run_job({w: (lambda w=w: w * 10) for w in range(4)}, handler)
            # sync: on return, everything has merged
            assert waiter.completed
            assert sorted(results) == [(0, 0), (1, 10), (2, 20), (3, 30)]
        finally:
            sched.shutdown()

    def test_async_mode_returns_immediately(self):
        sched = JobScheduler(num_workers=2)
        try:
            results, handler = collector()
            gate = threading.Event()

            def slow(w):
                gate.wait(5)
                return w

            sched.set_mode(ASYNC)
            # first job always blocks (warm-up parity) -- use a fast one
            sched.run_job({0: lambda: 0, 1: lambda: 1}, handler)
            results.clear()
            t0 = time.monotonic()
            waiter = sched.run_job({w: (lambda w=w: slow(w)) for w in range(2)}, handler)
            submit_elapsed = time.monotonic() - t0
            assert submit_elapsed < 1.0  # returned before tasks finished
            assert not waiter.completed
            gate.set()
            waiter.await_result(timeout=5)
            assert sorted(results) == [(0, 0), (1, 1)]
        finally:
            sched.shutdown()

    def test_first_iteration_blocks_even_in_async_mode(self):
        sched = JobScheduler(num_workers=2)
        try:
            results, handler = collector()
            sched.set_mode(ASYNC)
            waiter = sched.run_job({0: lambda: "a", 1: lambda: "b"}, handler)
            # DAGScheduler.scala:641-663 -- first iteration always blocks
            assert waiter.completed
            assert len(results) == 2
        finally:
            sched.shutdown()

    def test_results_stream_per_worker_not_at_barrier(self):
        """Per-partition streaming: a fast worker's result is merged while a
        slow worker is still running (the whole point of ASYNCreduce)."""
        sched = JobScheduler(num_workers=2)
        try:
            results, handler = collector()
            sched.run_job({0: lambda: 0, 1: lambda: 1}, handler)  # warm-up
            results.clear()
            slow_gate = threading.Event()
            sched.set_mode(ASYNC)
            waiter = sched.run_job(
                {0: lambda: "fast", 1: lambda: (slow_gate.wait(5), "slow")[1]}, handler
            )
            deadline = time.monotonic() + 5
            while not results and time.monotonic() < deadline:
                time.sleep(0.005)
            assert results == [(0, "fast")]  # fast merged, slow still out
            slow_gate.set()
            waiter.await_result(timeout=5)
            assert sorted(results) == [(0, "fast"), (1, "slow")]
        finally:
            sched.shutdown()


class TestRetryAndFailure:
    def test_flaky_task_retried_until_success(self):
        sched = JobScheduler(num_workers=1, max_task_failures=4)
        try:
            results, handler = collector()
            attempts = {"n": 0}

            def flaky():
                attempts["n"] += 1
                if attempts["n"] < 3:
                    raise RuntimeError("transient")
                return "ok"

            waiter = sched.run_job({0: flaky}, handler)  # sync first iter
            assert waiter.completed
            assert attempts["n"] == 3
            assert results == [(0, "ok")]
        finally:
            sched.shutdown()

    def test_permanent_failure_aborts_job(self):
        sched = JobScheduler(num_workers=1, max_task_failures=3)
        try:
            def always_fail():
                raise ValueError("boom")

            with pytest.raises(RuntimeError, match="failed 3 times"):
                sched.run_job({0: always_fail}, lambda w, r: None)
        finally:
            sched.shutdown()

    def test_executor_loss_resubmits_inflight_tasks(self):
        """DistributedSuite analog: kill a worker mid-task; the monitor
        declares it lost, the scheduler replaces it and the job completes."""
        sched = JobScheduler(num_workers=2)
        try:
            results, handler = collector()
            sched.run_job({0: lambda: 0, 1: lambda: 1}, handler)  # warm-up
            results.clear()
            sched.set_mode(ASYNC)
            release = threading.Event()
            ran_on = []

            def task0():
                ran_on.append(threading.current_thread().name)
                if not release.is_set():
                    # first attempt hangs until killed; retry returns fast
                    time.sleep(30)
                return "recovered"

            waiter = sched.run_job({0: task0, 1: lambda: "fine"}, handler)
            monitor = HeartbeatMonitor(
                sched.pool, sched.on_executor_lost, timeout_ms=1e9
            )
            deadline = time.monotonic() + 5
            while len(ran_on) < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            release.set()
            sched.pool.kill(0)  # worker dies mid-task
            lost = monitor.check_once()
            assert 0 in lost
            waiter.await_result(timeout=10)
            assert ("1", "fine") not in results  # sanity: tuple shape is (wid, res)
            assert sorted(results) == [(0, "recovered"), (1, "fine")]
            assert len(ran_on) == 2  # original + resubmitted attempt
        finally:
            sched.shutdown()

    def test_heartbeat_busy_executor_not_declared_dead(self):
        sched = JobScheduler(num_workers=1)
        try:
            gate = threading.Event()
            sched.run_job({0: lambda: "warm"}, lambda w, r: None)
            sched.set_mode(ASYNC)
            sched.run_job({0: lambda: gate.wait(5) or "x"}, lambda w, r: None)
            time.sleep(0.1)  # let the executor pick the task up
            monitor = HeartbeatMonitor(
                sched.pool, sched.on_executor_lost, timeout_ms=0.0
            )
            assert monitor.check_once() == []  # busy != dead despite 0 timeout
            gate.set()
        finally:
            sched.shutdown()


class TestBarrier:
    def test_unseen_workers_always_selected(self):
        ctx = AsyncContext()
        cohort = partial_barrier(ctx, 4, lambda ws: False)
        assert cohort == [0, 1, 2, 3]

    def test_busy_workers_excluded(self):
        ctx = AsyncContext()
        for w in range(4):
            ctx.merge_result(w, None, 0, 1.0, 1)  # all available
        ctx.mark_busy([1, 3])
        cohort = partial_barrier(ctx, 4, lambda ws: True)
        assert cohort == [0, 2]

    def test_bucket_predicate_thresholds(self):
        ctx = AsyncContext()
        for w in range(4):
            ctx.merge_result(w, None, 0, 1.0, 1)
        ctx.mark_busy([0, 1, 2])  # 1 of 4 available
        pred = bucket_predicate(ctx, 4, bucket_ratio=0.5)  # needs >= 2
        assert partial_barrier(ctx, 4, pred) == []
        ctx.mark_available(0)  # 2 of 4 available
        assert partial_barrier(ctx, 4, pred) == [0, 3]


class TestStraggler:
    def test_cloud_cohort_reference_pattern(self):
        # numPart=32: length=8, normal=6, longtail=2 -> ids c*4
        normal, long_tail = build_cloud_stragglers(32)
        assert long_tail == [0, 4]
        assert normal == [8, 12, 16, 20, 24, 28]

    def test_no_delay_before_calibration(self):
        m = DelayModel(coeff=1.0, num_workers=8)
        assert m.delay_ms(0) == 0.0
        m.calibrate(100.0)
        assert m.delay_ms(0) == 100.0
        assert m.delay_ms(1) == 0.0

    def test_cloud_mode_delay_ranges(self):
        m = DelayModel(coeff=-1, num_workers=32, seed=1)
        m.calibrate(100.0)
        for _ in range(20):
            lt = m.delay_ms(0)  # long-tail worker
            assert lt == 0 or 250 <= lt <= 1000
            nm = m.delay_ms(8)  # normal straggler
            assert nm == 0 or 150 <= nm <= 250
        assert m.delay_ms(3) == 0.0  # non-straggler

    def test_disabled_model(self):
        m = DelayModel(coeff=0.0, num_workers=8)
        m.calibrate(100.0)
        assert not m.enabled
        assert m.delay_ms(0) == 0.0
