"""Round-5 SQL plan work (VERDICT r4 #3/#4): join reordering, CTE
memoization, and the logical plan extended past the FROM/JOIN/WHERE core
(Sort / Limit / Window / SetOp / Distinct nodes with pushdown + pruning
rules crossing them).

Parity targets: ``sql/catalyst/.../optimizer/joins.scala:37`` (ReorderJoin)
and ``CostBasedJoinReorder.scala:35`` for the ordering;
``Optimizer.scala:38`` batches for the clause-crossing rewrites; InlineCTE
for the execute-once/inline split.  Structural assertions use the public
``explain`` artifact; every rewrite is also checked result-equivalent
against the unoptimized plan.
"""

import time

import numpy as np
import pytest

from asyncframework_tpu.sql import ColumnarFrame, col, sql
from asyncframework_tpu.sql.parser import SQLContext
from asyncframework_tpu.sql.plan import (
    Compute,
    Distinct,
    Filter,
    Join,
    Limit,
    Scan,
    SetOp,
    Shared,
    Sort,
    Window,
    clone_plan,
    execute,
    node_columns,
    optimize,
)


def _frames_star(n_fact=3000, n_keys=50, dim_keys=(0, 1), seed=0):
    """Two fact tables sharing key k, plus a tiny dimension restricted to
    ``dim_keys`` -- the shape where written-order F1 JOIN F2 builds a huge
    intermediate and greedy D-first stays small."""
    rs = np.random.default_rng(seed)
    f1 = ColumnarFrame({
        "k": rs.integers(0, n_keys, n_fact).astype(np.int32),
        "x": rs.normal(size=n_fact).astype(np.float32),
    })
    f2 = ColumnarFrame({
        "k": rs.integers(0, n_keys, n_fact).astype(np.int32),
        "y": rs.normal(size=n_fact).astype(np.float32),
    })
    d = ColumnarFrame({
        "k": np.asarray(dim_keys, np.int32),
        "z": np.arange(len(dim_keys), dtype=np.float32),
    })
    return f1, f2, d


class TestJoinReorder:
    def test_small_relation_moves_first(self):
        f1, f2, d = _frames_star()
        ctx = SQLContext()
        ctx.register("f1", f1)
        ctx.register("f2", f2)
        ctx.register("d", d)
        txt = ctx.explain(
            "SELECT k, x, y, z FROM f1 JOIN f2 ON k JOIN d ON k"
        )
        # greedy order: d (2 rows) first, then the facts
        assert txt.index("Scan(d") < txt.index("Scan(f1")
        assert txt.index("Scan(f1") < txt.index("Scan(f2")

    def test_reorder_result_equivalent(self):
        f1, f2, d = _frames_star(n_fact=400, n_keys=10)
        plan = Join(
            Join(Scan("f1", frame=f1), Scan("f2", frame=f2), on="k"),
            Scan("d", frame=d), on="k",
        )
        naive = execute(clone_plan(plan))
        opt_plan = optimize(plan, required=None)
        opt = execute(opt_plan)
        assert sorted(naive.columns) == sorted(opt.columns)
        key = lambda f: sorted(
            tuple(round(float(v), 4) for v in row) for row in (
                zip(*[np.asarray(f[c]).tolist() for c in naive.columns])
            )
        )
        assert key(naive) == key(opt)

    def test_column_order_preserved_by_project_wrap(self):
        f1, f2, d = _frames_star(n_fact=100, n_keys=5)
        plan = Join(
            Join(Scan("f1", frame=f1), Scan("f2", frame=f2), on="k"),
            Scan("d", frame=d), on="k",
        )
        orig_cols = node_columns(clone_plan(plan))
        out = execute(optimize(plan, required=None))
        assert out.columns == orig_cols

    def test_filtered_relation_estimate_reorders(self):
        # an unfiltered small-ish table vs a filtered big one: the filter's
        # selectivity decay should pull the filtered scan forward
        rs = np.random.default_rng(1)
        big = ColumnarFrame({
            "k": rs.integers(0, 20, 2000).astype(np.int32),
            "x": rs.normal(size=2000).astype(np.float32),
        })
        mid = ColumnarFrame({
            "k": rs.integers(0, 20, 500).astype(np.int32),
            "w": rs.normal(size=500).astype(np.float32),
        })
        d = ColumnarFrame({
            "k": np.asarray([3], np.int32),
            "z": np.asarray([1.0], np.float32),
        })
        out = sql(
            "SELECT k, x, w, z FROM big JOIN mid ON k JOIN d ON k "
            "WHERE x > 100", big=big, mid=mid, d=d,
        )
        assert len(out) == 0  # x > 100 empties it; shape checked above all

    def test_left_join_chain_not_reordered(self):
        f1, f2, d = _frames_star(n_fact=50, n_keys=5)
        ctx = SQLContext()
        ctx.register("f1", f1)
        ctx.register("f2", f2)
        ctx.register("d", d)
        txt = ctx.explain(
            "SELECT k, x, y, z FROM f1 LEFT JOIN f2 ON k LEFT JOIN d ON k"
        )
        # outer joins are order-sensitive: written order stands
        assert txt.index("Scan(f1") < txt.index("Scan(f2")
        assert txt.index("Scan(f2") < txt.index("Scan(d")

    def test_nonkey_collision_keeps_written_order(self):
        # both facts carry a non-key column "x": reordering could change
        # which side receives the _right suffix -- must keep written order
        rs = np.random.default_rng(2)
        f1 = ColumnarFrame({
            "k": rs.integers(0, 5, 50).astype(np.int32),
            "x": rs.normal(size=50).astype(np.float32),
        })
        f2 = ColumnarFrame({
            "k": rs.integers(0, 5, 50).astype(np.int32),
            "x": rs.normal(size=50).astype(np.float32),
        })
        d = ColumnarFrame({
            "k": np.asarray([1], np.int32),
            "z": np.asarray([9.0], np.float32),
        })
        plan = Join(
            Join(Scan("f1", frame=f1), Scan("f2", frame=f2), on="k"),
            Scan("d", frame=d), on="k",
        )
        expect = execute(clone_plan(plan))
        got = execute(optimize(plan, required=None))
        assert got.columns == expect.columns  # x / x_right naming intact

    @pytest.mark.slow
    def test_star_query_measured_win(self):
        """The VERDICT's done-criterion: a measured win on a badly written
        3-table star query.  Written order builds a ~12M-row intermediate;
        greedy builds ~hundreds."""
        f1, f2, d = _frames_star(n_fact=25_000, n_keys=50)
        plan_bad = Join(
            Join(Scan("f1", frame=f1), Scan("f2", frame=f2), on="k"),
            Scan("d", frame=d), on="k",
        )
        plan_opt = optimize(clone_plan(plan_bad), required=None)
        # warm both paths once at small scale implicitly via earlier tests;
        # time medians of 3
        def med(fn):
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                fn()
                ts.append(time.perf_counter() - t0)
            return sorted(ts)[1]

        t_naive = med(lambda: execute(clone_plan(plan_bad)))
        t_opt = med(lambda: execute(clone_plan(plan_opt)))
        # the intermediate-size gap is ~4 orders of magnitude; demand 2x
        # to stay robust on noisy CI
        assert t_opt * 2 < t_naive, (t_opt, t_naive)


class TestCTEMemoization:
    def _counting_ctx(self):
        ctx = SQLContext()
        calls = {"n": 0}

        def bump(x):
            calls["n"] += 1
            return x

        ctx.register_udf("bump", bump)
        ctx.register("t", ColumnarFrame({
            "a": np.asarray([1.0, 2.0, 3.0], np.float32),
        }))
        return ctx, calls

    def test_twice_referenced_cte_executes_once(self):
        ctx, calls = self._counting_ctx()
        out = ctx.sql(
            "WITH c AS (SELECT bump(a) AS a FROM t) "
            "SELECT a FROM c UNION ALL SELECT a FROM c"
        )
        assert len(out) == 6
        assert calls["n"] == 3  # 3 rows, ONE body execution

    def test_self_join_cte_executes_once(self):
        ctx, calls = self._counting_ctx()
        out = ctx.sql(
            "WITH c AS (SELECT bump(a) AS a FROM t) "
            "SELECT a FROM c JOIN c ON a"
        )
        assert len(out) == 3
        assert calls["n"] == 3

    def test_unreferenced_cte_never_executes(self):
        ctx, calls = self._counting_ctx()
        out = ctx.sql(
            "WITH c AS (SELECT bump(a) AS a FROM t) SELECT a FROM t"
        )
        assert len(out) == 3
        assert calls["n"] == 0

    def test_single_use_cte_inlines_for_pushdown(self, tmp_path):
        path = tmp_path / "t.csv"
        path.write_text("a,b,unused\n1,10,0\n2,20,0\n3,30,0\n")
        ctx = SQLContext()
        ctx.register_csv("t", str(path))
        txt = ctx.explain(
            "WITH c AS (SELECT a, b, unused FROM t) "
            "SELECT a FROM c WHERE b > 15"
        )
        # inlined: the predicate and the pruned projection reached the
        # reader scan -- no Shared boundary in the way
        assert "Shared" not in txt
        assert "where=" in txt
        out = ctx.sql(
            "WITH c AS (SELECT a, b, unused FROM t) "
            "SELECT a FROM c WHERE b > 15"
        )
        assert sorted(a for (a,) in out.collect()) == [2, 3]

    def test_multi_use_cte_is_boundary(self):
        ctx = SQLContext()
        ctx.register("t", ColumnarFrame({
            "a": np.asarray([1, 2, 3], np.int32),
            "b": np.asarray([10.0, 20.0, 30.0], np.float32),
        }))
        txt = ctx.explain(
            "WITH c AS (SELECT a, b FROM t) "
            "SELECT a FROM c WHERE a > 1 UNION ALL SELECT a FROM c"
        )
        assert txt.count("Shared(c)") == 2  # same body, two references

    def test_cte_in_subquery_and_from_executes_once(self):
        # the IN-subquery runs at parse time; it must populate the
        # statement-wide Shared cache, not a private inlined copy
        ctx, calls = self._counting_ctx()
        out = ctx.sql(
            "WITH c AS (SELECT bump(a) AS a FROM t) "
            "SELECT a FROM c WHERE a IN (SELECT a FROM c)"
        )
        assert sorted(a for (a,) in out.collect()) == [1.0, 2.0, 3.0]
        assert calls["n"] == 3  # ONE body execution across both positions

    @pytest.mark.slow
    def test_twice_referenced_cte_measured_win(self):
        """VERDICT done-criterion: measured win on a twice-referenced CTE
        (body = an aggregation over 2M rows; memoized = one execution)."""
        rs = np.random.default_rng(7)
        n = 2_000_000
        ctx = SQLContext()
        ctx.register("big", ColumnarFrame({
            "k": rs.integers(0, 1000, n).astype(np.int32),
            "v": rs.normal(size=n).astype(np.float32),
        }))
        q_body = "SELECT k, SUM(v) AS s FROM big GROUP BY k"
        two_ref = (f"WITH c AS ({q_body}) "
                   "SELECT s FROM c UNION ALL SELECT s FROM c")

        def run_once():
            return ctx.sql(two_ref)

        def run_naive():
            # the pre-memoization equivalent: execute the body twice
            a = ctx.sql(q_body)
            b = ctx.sql(q_body)
            return a.select("s").union_all(b.select("s"))

        def med(fn):
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                fn()
                ts.append(time.perf_counter() - t0)
            return sorted(ts)[1]

        med(run_once)  # warm caches
        t_memo = med(run_once)
        t_naive = med(run_naive)
        assert t_memo * 1.4 < t_naive, (t_memo, t_naive)
        assert len(run_once()) == 2000


class TestWindowNode:
    def _ctx(self):
        ctx = SQLContext()
        ctx.register("t", ColumnarFrame({
            "k": np.asarray([1, 1, 2, 2, 2, 3], np.int32),
            "v": np.asarray([5.0, 3.0, 9.0, 2.0, 7.0, 1.0], np.float32),
        }))
        return ctx

    def test_partition_key_predicate_sinks_below_window(self):
        ctx = self._ctx()
        q = ("SELECT k, v, rn FROM (SELECT k, v, ROW_NUMBER() OVER "
             "(PARTITION BY k ORDER BY v) AS rn FROM t) WHERE k = 2")
        txt = ctx.explain(q)
        assert "Window" in txt
        # the Filter ended up BELOW the Window node (deeper indentation,
        # later in the pre-order text)
        assert txt.index("Window") < txt.index("Filter")
        out = ctx.sql(q)
        got = {(r[0], r[1]): r[2] for r in out.collect()}
        # rn computed over the FULL k=2 partition, post-filter identical
        assert got[(2, 2.0)] == 1 and got[(2, 7.0)] == 2 and got[(2, 9.0)] == 3

    def test_non_partition_predicate_stays_above_window(self):
        ctx = self._ctx()
        q = ("SELECT k, v, rn FROM (SELECT k, v, ROW_NUMBER() OVER "
             "(PARTITION BY k ORDER BY v) AS rn FROM t) WHERE v > 4")
        txt = ctx.explain(q)
        assert txt.index("Filter") < txt.index("Window")
        out = ctx.sql(q)
        got = {(r[0], r[1]): r[2] for r in out.collect()}
        # rn reflects the FULL partitions: (2, 7.0) is rank 2 of k=2 even
        # though 2.0 was filtered from the result
        assert got[(2, 7.0)] == 2
        assert got[(1, 5.0)] == 2

    def test_window_output_predicate_stays_above(self):
        ctx = self._ctx()
        q = ("SELECT k, v, rn FROM (SELECT k, v, ROW_NUMBER() OVER "
             "(PARTITION BY k ORDER BY v) AS rn FROM t) WHERE rn = 1")
        txt = ctx.explain(q)
        assert txt.index("Filter") < txt.index("Window")
        out = ctx.sql(q)
        assert sorted((r[0], r[1]) for r in out.collect()) == [
            (1, 3.0), (2, 2.0), (3, 1.0),
        ]

    def test_adjacent_computes_collapse(self):
        ctx = self._ctx()
        q = ("SELECT k, v, rn FROM (SELECT k, v, ROW_NUMBER() OVER "
             "(PARTITION BY k ORDER BY v) AS rn FROM t)")
        txt = ctx.explain(q)
        assert txt.count("Compute") == 1  # outer re-projection fused away
        out = ctx.sql(q)
        assert out.columns == ["k", "v", "rn"]

    def test_window_pruning_keeps_inputs(self, tmp_path):
        path = tmp_path / "t.csv"
        path.write_text("k,v,unused\n1,5,0\n1,3,0\n2,9,0\n")
        ctx = SQLContext()
        ctx.register_csv("t", str(path))
        txt = ctx.explain(
            "SELECT rn FROM (SELECT k, v, ROW_NUMBER() OVER "
            "(PARTITION BY k ORDER BY v) AS rn FROM t)"
        )
        assert "unused" not in txt.split("Scan")[1]  # pruned from the scan
        out = ctx.sql(
            "SELECT rn FROM (SELECT k, v, ROW_NUMBER() OVER "
            "(PARTITION BY k ORDER BY v) AS rn FROM t)"
        )
        assert sorted(r for (r,) in out.collect()) == [1, 1, 2]


class TestSetOpNode:
    def _csv_ctx(self, tmp_path):
        p1 = tmp_path / "t1.csv"
        p1.write_text("a,b,unused\n1,10,0\n2,20,0\n")
        p2 = tmp_path / "t2.csv"
        p2.write_text("a,b,unused\n3,30,0\n4,40,0\n")
        ctx = SQLContext()
        ctx.register_csv("t1", str(p1))
        ctx.register_csv("t2", str(p2))
        return ctx

    def test_pruning_crosses_union_all(self, tmp_path):
        ctx = self._csv_ctx(tmp_path)
        q = ("SELECT a FROM (SELECT * FROM t1 UNION ALL SELECT * FROM t2)")
        txt = ctx.explain(q)
        # both reader scans pruned to the single required column
        assert txt.count("select=['a']") == 2
        out = ctx.sql(q)
        assert sorted(a for (a,) in out.collect()) == [1, 2, 3, 4]

    def test_predicate_pushes_into_both_branches(self, tmp_path):
        ctx = self._csv_ctx(tmp_path)
        q = ("SELECT a FROM (SELECT * FROM t1 UNION ALL SELECT * FROM t2) "
             "WHERE a > 1")
        txt = ctx.explain(q)
        assert txt.count("where=") == 2  # reached BOTH readers
        out = ctx.sql(q)
        assert sorted(a for (a,) in out.collect()) == [2, 3, 4]

    def test_distinct_setop_children_not_pruned(self, tmp_path):
        ctx = self._csv_ctx(tmp_path)
        q = "SELECT a FROM (SELECT * FROM t1 UNION SELECT * FROM t2)"
        txt = ctx.explain(q)
        # UNION (distinct) compares whole rows: scans keep all columns
        assert "select=['a']" not in txt
        out = ctx.sql(q)
        assert sorted(a for (a,) in out.collect()) == [1, 2, 3, 4]

    def test_predicate_pushes_through_except_and_intersect(self):
        f = ColumnarFrame({"a": np.asarray([1, 2, 3, 4], np.int32)})
        g = ColumnarFrame({"a": np.asarray([3, 4, 5], np.int32)})
        out = sql(
            "SELECT a FROM (SELECT a FROM t EXCEPT SELECT a FROM u) "
            "WHERE a > 1", t=f, u=g,
        )
        assert sorted(a for (a,) in out.collect()) == [2]
        out = sql(
            "SELECT a FROM (SELECT a FROM t INTERSECT SELECT a FROM u) "
            "WHERE a > 3", t=f, u=g,
        )
        assert sorted(a for (a,) in out.collect()) == [4]


class TestSortLimitDistinctNodes:
    def test_order_limit_become_plan_nodes(self):
        ctx = SQLContext()
        ctx.register("t", ColumnarFrame({
            "a": np.asarray([3, 1, 2], np.int32),
        }))
        txt = ctx.explain("SELECT a FROM t ORDER BY a DESC LIMIT 2")
        assert "Limit(2)" in txt and "Sort" in txt
        out = ctx.sql("SELECT a FROM t ORDER BY a DESC LIMIT 2")
        assert [a for (a,) in out.collect()] == [3, 2]

    def test_filter_pushes_through_derived_sort(self, tmp_path):
        path = tmp_path / "t.csv"
        path.write_text("a,b\n3,1\n1,2\n2,3\n")
        ctx = SQLContext()
        ctx.register_csv("t", str(path))
        q = ("SELECT a FROM (SELECT a, b FROM t ORDER BY b) WHERE a > 1")
        txt = ctx.explain(q)
        assert "where=" in txt  # crossed the Sort into the reader
        out = ctx.sql(q)
        assert [a for (a,) in out.collect()] == [3, 2]  # b-order kept

    def test_filter_blocked_by_limit(self):
        f = ColumnarFrame({"a": np.asarray([5, 1, 4, 2], np.int32)})
        q = ("SELECT a FROM (SELECT a FROM t ORDER BY a LIMIT 2) "
             "WHERE a > 1")
        ctx = SQLContext()
        ctx.register("t", f)
        txt = ctx.explain(q)
        assert txt.index("Filter") < txt.index("Limit")
        out = ctx.sql(q)
        # LIMIT 2 keeps [1, 2]; filter then keeps [2] -- NOT [2, 4]
        assert [a for (a,) in out.collect()] == [2]

    def test_distinct_node_and_filter_pushes_through(self):
        f = ColumnarFrame({
            "a": np.asarray([1, 1, 2, 3, 3], np.int32),
        })
        ctx = SQLContext()
        ctx.register("t", f)
        txt = ctx.explain(
            "SELECT a FROM (SELECT DISTINCT a FROM t) WHERE a > 1"
        )
        assert "Distinct" in txt
        assert txt.index("Distinct") < txt.index("Filter")
        out = ctx.sql(
            "SELECT a FROM (SELECT DISTINCT a FROM t) WHERE a > 1"
        )
        assert sorted(a for (a,) in out.collect()) == [2, 3]


class TestViewDDL:
    def _ctx(self):
        ctx = SQLContext()
        ctx.register("t", ColumnarFrame({
            "k": np.asarray([1, 1, 2], np.int32),
            "v": np.asarray([10.0, 20.0, 5.0], np.float32),
        }))
        return ctx

    def test_create_view_then_query(self):
        ctx = self._ctx()
        out = ctx.sql(
            "CREATE VIEW sums AS SELECT k, SUM(v) AS s FROM t GROUP BY k"
        )
        assert out.collect() == [("sums",)]
        got = ctx.sql("SELECT s FROM sums WHERE k = 1")
        assert [s for (s,) in got.collect()] == [30.0]

    def test_create_without_replace_rejects_existing(self):
        ctx = self._ctx()
        ctx.sql("CREATE VIEW x AS SELECT k FROM t")
        with pytest.raises(ValueError, match="OR REPLACE"):
            ctx.sql("CREATE VIEW x AS SELECT v FROM t")
        ctx.sql("CREATE OR REPLACE VIEW x AS SELECT v FROM t")
        assert ctx.sql("SELECT * FROM x").columns == ["v"]

    def test_drop_view(self):
        ctx = self._ctx()
        ctx.sql("CREATE TEMP VIEW x AS SELECT k FROM t")
        ctx.sql("DROP VIEW x")
        with pytest.raises(KeyError):
            ctx.sql("SELECT * FROM x")
        ctx.sql("DROP VIEW IF EXISTS x")  # no error
        with pytest.raises(KeyError):
            ctx.sql("DROP VIEW x")

    def test_drop_view_refuses_base_tables(self):
        """ISSUE 1 satellite: DROP VIEW used to delete ANY registered name
        -- including base tables the caller registered via register() --
        silently unregistering real data.  Only CREATE VIEW names drop."""
        ctx = self._ctx()  # 't' is a register()ed base table
        with pytest.raises(ValueError, match="base table"):
            ctx.sql("DROP VIEW t")
        # IF EXISTS excuses absence, never the wrong object kind
        with pytest.raises(ValueError, match="base table"):
            ctx.sql("DROP VIEW IF EXISTS t")
        assert ctx.table("t") is not None  # still queryable
        # a name re-registered as a base table loses its view-ness
        ctx.sql("CREATE VIEW v AS SELECT k FROM t")
        ctx.sql("DROP VIEW v")  # fine while it is a view
        ctx.sql("CREATE VIEW v2 AS SELECT k FROM t")
        ctx.register("v2", ctx.table("t"))  # now a base table
        with pytest.raises(ValueError, match="base table"):
            ctx.sql("DROP VIEW v2")


class TestExplainStatement:
    def test_explain_returns_plan_frame(self):
        ctx = SQLContext()
        ctx.register("t", ColumnarFrame({
            "a": np.asarray([1, 2, 3], np.int32),
            "b": np.asarray([1.0, 2.0, 3.0], np.float32),
        }))
        out = ctx.sql("EXPLAIN SELECT a FROM t WHERE b > 1 ORDER BY a")
        assert out.columns == ["plan"]
        txt = "\n".join(np.asarray(out["plan"]).tolist())
        assert "Sort" in txt and "Compute" in txt and "Filter" in txt

    def test_explain_matches_context_explain(self):
        ctx = SQLContext()
        ctx.register("t", ColumnarFrame({
            "a": np.asarray([1, 2], np.int32),
        }))
        q = "SELECT a FROM t LIMIT 1"
        via_stmt = "\n".join(
            np.asarray(ctx.sql("EXPLAIN " + q)["plan"]).tolist()
        )
        assert via_stmt == ctx.explain(q)


class TestDerivedTableLaziness:
    def test_pushdown_crosses_derived_table(self, tmp_path):
        path = tmp_path / "t.csv"
        path.write_text("k,v,unused\n1,10,0\n2,20,0\n3,30,0\n")
        ctx = SQLContext()
        ctx.register_csv("t", str(path))
        q = "SELECT k FROM (SELECT k, v, unused FROM t) WHERE v > 15"
        txt = ctx.explain(q)
        assert "where=" in txt and "unused" not in txt.split("Scan")[1]
        out = ctx.sql(q)
        assert sorted(k for (k,) in out.collect()) == [2, 3]

    def test_aliased_derived_column_blocks_push(self):
        # SELECT a AS x ... WHERE x > 1: x does not exist below the
        # projection under that name; the filter stays above (correctness)
        f = ColumnarFrame({"a": np.asarray([1, 2, 3], np.int32)})
        out = sql(
            "SELECT x FROM (SELECT a AS x FROM t) WHERE x > 1", t=f,
        )
        assert sorted(x for (x,) in out.collect()) == [2, 3]

    def test_borrowed_order_by_is_planned(self):
        # ORDER BY mixing an alias with an unprojected source column used
        # to be an eager-fallback shape; round 5 plans it (borrow through
        # the Compute, Sort, drop via Project)
        f = ColumnarFrame({
            "a": np.asarray([1, 2, 3, 4], np.int32),
            "b": np.asarray([0, 1, 0, 1], np.int32),
        })
        out = sql("SELECT a AS x FROM t ORDER BY b, x DESC", t=f)
        assert out.columns == ["x"]
        assert [x for (x,) in out.collect()] == [3, 1, 4, 2]
        ctx = SQLContext()
        ctx.register("t", f)
        txt = ctx.explain("SELECT a AS x FROM t ORDER BY b, x DESC")
        assert "(eager)" not in txt  # no fallback Scan
        assert txt.index("Project") < txt.index("Sort")
        assert txt.index("Sort") < txt.index("Compute")

    def test_having_label_bridge_is_planned(self):
        f = ColumnarFrame({
            "k": np.asarray([1, 1, 2, 2], np.int32),
            "v": np.asarray([10.0, 20.0, 1.0, 2.0], np.float32),
        })
        ctx = SQLContext()
        ctx.register("t", f)
        # HAVING references the aggregate by CALL syntax while the SELECT
        # aliases it -- previously the eager bridge, now plan nodes
        q = ("SELECT k, SUM(v) AS total FROM t GROUP BY k "
             "HAVING SUM(v) > 25")
        txt = ctx.explain(q)
        assert "(eager)" not in txt
        assert txt.index("Filter") < txt.index("Aggregate")
        out = ctx.sql(q)
        assert out.columns == ["k", "total"]  # bridge column dropped
        assert out.collect() == [(1, 30.0)]
