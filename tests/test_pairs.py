"""Pair-RDD surface parity tests (PairRDDFunctions.scala analog).

Word-count, by-key aggregation, the four join flavors, cogroup, and
range-partitioned sortByKey -- the half of the RDD API the round-1 verdict
flagged as missing entirely.
"""

import numpy as np
import pytest

from asyncframework_tpu.data.dataset import DistributedDataset
from asyncframework_tpu.data.pairs import hash_partition, portable_hash
from asyncframework_tpu.engine.scheduler import JobScheduler


@pytest.fixture()
def sched():
    s = JobScheduler(num_workers=4)
    yield s
    s.shutdown()


def pairs(sched, data, parts=None):
    return DistributedDataset.from_list(sched, data, num_partitions=parts)


class TestPortableHash:
    def test_stable_across_types(self):
        assert portable_hash("spark") == portable_hash("spark")
        assert portable_hash(("a", 1)) == portable_hash(("a", 1))
        assert portable_hash(7) == 7
        assert portable_hash(None) == 0

    def test_partition_in_range(self):
        for k in ["x", "y", 42, -3, ("t", 1), None, 2.5]:
            assert 0 <= hash_partition(k, 4) < 4

    def test_unstable_type_rejected(self):
        with pytest.raises(TypeError):
            portable_hash(object())


class TestByKey:
    def test_word_count(self, sched):
        text = "the quick brown fox jumps over the lazy dog the end".split()
        counts = dict(
            pairs(sched, text)
            .map(lambda w: (w, 1))
            .reduce_by_key(lambda a, b: a + b)
            .collect()
        )
        assert counts["the"] == 3
        assert counts["fox"] == 1
        assert sum(counts.values()) == len(text)

    def test_reduce_by_key_copartitions_same_key(self, sched):
        data = [(i % 7, i) for i in range(100)]
        ds = pairs(sched, data).reduce_by_key(lambda a, b: a + b)
        # every key appears exactly once globally
        keys = [k for k, _ in ds.collect()]
        assert sorted(keys) == sorted(set(keys))
        expect = {k: sum(i for i in range(100) if i % 7 == k) for k in range(7)}
        assert dict(ds.collect()) == expect

    def test_group_by_key(self, sched):
        data = [("a", 1), ("b", 2), ("a", 3), ("c", 4), ("b", 5)]
        grouped = dict(pairs(sched, data).group_by_key().collect())
        assert sorted(grouped["a"]) == [1, 3]
        assert sorted(grouped["b"]) == [2, 5]
        assert grouped["c"] == [4]

    def test_fold_by_key(self, sched):
        data = [("x", 2), ("x", 3), ("y", 4)]
        out = dict(pairs(sched, data).fold_by_key(10, lambda a, b: a + b).collect())
        # zero applied once per (partition, key) on the map side, like foldByKey
        assert out["y"] == 14
        assert out["x"] >= 15  # 2+3 plus at least one zero

    def test_count_by_key(self, sched):
        data = [("a", 1), ("a", 2), ("b", 3)]
        assert pairs(sched, data).count_by_key() == {"a": 2, "b": 1}

    def test_map_values_flat_map_values_keys_values(self, sched):
        data = [("a", 1), ("b", 2)]
        ds = pairs(sched, data)
        assert dict(ds.map_values(lambda v: v * 10).collect()) == {"a": 10, "b": 20}
        assert sorted(ds.keys().collect()) == ["a", "b"]
        assert sorted(ds.values().collect()) == [1, 2]
        fm = ds.flat_map_values(lambda v: [v, v]).collect()
        assert sorted(fm) == [("a", 1), ("a", 1), ("b", 2), ("b", 2)]

    def test_partition_by_places_by_hash(self, sched):
        data = [(k, 0) for k in range(20)]
        ds = pairs(sched, data).partition_by(4)
        for pid in ds.partition_ids():
            for k, _ in ds._compute(pid):
                assert hash_partition(k, 4) == pid


class TestJoins:
    L = [("a", 1), ("b", 2), ("c", 3), ("a", 4)]
    R = [("a", "x"), ("b", "y"), ("d", "z")]

    def test_inner_join(self, sched):
        out = sorted(pairs(sched, self.L).join(pairs(sched, self.R)).collect())
        assert out == [("a", (1, "x")), ("a", (4, "x")), ("b", (2, "y"))]

    def test_left_outer_join(self, sched):
        out = sorted(
            pairs(sched, self.L).left_outer_join(pairs(sched, self.R)).collect()
        )
        assert ("c", (3, None)) in out
        assert ("a", (1, "x")) in out
        assert not any(k == "d" for k, _ in out)

    def test_right_outer_join(self, sched):
        out = sorted(
            pairs(sched, self.L).right_outer_join(pairs(sched, self.R)).collect()
        )
        assert ("d", (None, "z")) in out
        assert not any(k == "c" for k, _ in out)

    def test_full_outer_join(self, sched):
        out = sorted(
            pairs(sched, self.L).full_outer_join(pairs(sched, self.R)).collect()
        )
        assert ("c", (3, None)) in out and ("d", (None, "z")) in out

    def test_cogroup(self, sched):
        co = dict(pairs(sched, self.L).cogroup(pairs(sched, self.R)).collect())
        vs, ws = co["a"]
        assert sorted(vs) == [1, 4] and ws == ["x"]
        assert co["d"] == ([], ["z"])


class TestSortByKey:
    def test_global_order_ascending(self, sched):
        import random

        rng = random.Random(7)
        data = [(rng.randint(0, 1000), i) for i in range(200)]
        ds = pairs(sched, data).sort_by_key()
        got = [k for k, _ in ds.collect()]  # collect is in partition order
        assert got == sorted(k for k, _ in data)

    def test_global_order_descending(self, sched):
        data = [(k, 0) for k in [5, 3, 9, 1, 7, 2]]
        got = [k for k, _ in pairs(sched, data).sort_by_key(False).collect()]
        assert got == [9, 7, 5, 3, 2, 1]

    def test_empty(self, sched):
        assert pairs(sched, []).sort_by_key().collect() == []


class TestSampleByKey:
    def test_fractions_respected(self, sched):
        data = [("a", i) for i in range(2000)] + [("b", i) for i in range(2000)]
        ds = pairs(sched, data)
        got = ds.sample_by_key({"a": 0.5, "b": 0.1}, seed=3).collect()
        ca = sum(1 for k, _ in got if k == "a")
        cb = sum(1 for k, _ in got if k == "b")
        assert 850 < ca < 1150
        assert 120 < cb < 290
        # keys not in fractions are dropped entirely
        got2 = ds.sample_by_key({"a": 1.0}, seed=3).collect()
        assert all(k == "a" for k, _ in got2)
        assert len(got2) == 2000

    def test_deterministic(self, sched):
        data = [(i % 5, i) for i in range(500)]
        ds = pairs(sched, data)
        f = {k: 0.3 for k in range(5)}
        assert ds.sample_by_key(f, seed=9).collect() == \
            ds.sample_by_key(f, seed=9).collect()


@pytest.fixture(params=["device", "host"])
def plane(request):
    """Run the array data plane both ways: the jitted device shuffle and
    the vectorized host shuffle (round 5's backend-dispatched twin)."""
    from asyncframework_tpu.conf import AsyncConf, set_global_conf

    set_global_conf(AsyncConf({"async.shuffle.data.plane": request.param}))
    yield request.param
    set_global_conf(None)


class TestDeviceShuffle:
    """reduce_by_key over array-typed partitions: the jitted hash-partition
    + all_to_all + segment-reduce data plane (ops/shuffle.py) AND its
    vectorized host twin, checked against the driver-routed path on
    identical data."""

    def _word_count_data(self, n, vocab, parts, seed=0):
        rs = np.random.default_rng(seed)
        keys = rs.integers(0, vocab, size=n).astype(np.int32)
        vals = np.ones(n, np.float32)
        per = n // parts
        return {
            w: (keys[w * per:(w + 1) * per], vals[w * per:(w + 1) * per])
            for w in range(parts)
        }

    def _merged(self, ds):
        out = {}
        for row in ds.collect():
            k_arr, v_arr = row
            for k, v in zip(np.asarray(k_arr), np.asarray(v_arr)):
                assert int(k) not in out, "key appears in two partitions"
                out[int(k)] = float(v)
        return out

    def test_device_matches_host_wordcount(self, plane):
        import time as _time

        from asyncframework_tpu.engine.scheduler import JobScheduler

        sched = JobScheduler(num_workers=8)
        blocks = self._word_count_data(200_000, 5_000, 8)
        dev_ds = DistributedDataset.from_array_pairs(sched, blocks)
        t0 = _time.monotonic()
        dev_out = self._merged(dev_ds.reduce_by_key("sum"))
        t_dev = _time.monotonic() - t0

        pairs = [
            (int(k), float(v))
            for w in range(8)
            for k, v in zip(*blocks[w])
        ]
        host_ds = DistributedDataset.from_list(sched, pairs)
        t0 = _time.monotonic()
        host_out = dict(
            host_ds.reduce_by_key(lambda a, b: a + b).collect()
        )
        t_host = _time.monotonic() - t0
        sched.shutdown()
        assert dev_out.keys() == host_out.keys()
        for k in host_out:
            assert dev_out[k] == pytest.approx(host_out[k])
        print(f"\n# shuffle 2e5 pairs: device {t_dev:.3f}s host {t_host:.3f}s "
              f"({t_host / max(t_dev, 1e-9):.1f}x)")

    @pytest.mark.parametrize("op,npop", [
        ("sum", np.add.reduce), ("max", np.maximum.reduce),
        ("min", np.minimum.reduce),
    ])
    def test_ops_against_numpy_oracle(self, op, npop, plane):
        from asyncframework_tpu.engine.scheduler import JobScheduler

        sched = JobScheduler(num_workers=4)
        rs = np.random.default_rng(3)
        blocks = {
            w: (rs.integers(0, 50, size=256).astype(np.int32),
                rs.normal(size=256).astype(np.float32))
            for w in range(4)
        }
        ds = DistributedDataset.from_array_pairs(sched, blocks)
        got = self._merged(ds.reduce_by_key(op))
        sched.shutdown()
        want = {}
        for w in range(4):
            for k, v in zip(*blocks[w]):
                want.setdefault(int(k), []).append(float(v))
        for k, vs in want.items():
            assert got[k] == pytest.approx(npop(vs), rel=1e-5), (k, op)

    def test_partitioning_is_key_mod_p(self, plane):
        from asyncframework_tpu.engine.scheduler import JobScheduler

        sched = JobScheduler(num_workers=4)
        blocks = {
            w: (np.arange(w * 8, w * 8 + 8, dtype=np.int32),
                np.ones(8, np.float32))
            for w in range(4)
        }
        ds = DistributedDataset.from_array_pairs(sched, blocks)
        out = ds.reduce_by_key("sum")
        for pid, payload in enumerate(
            out._compute(w) for w in out.partition_ids()
        ):
            k_arr, _ = payload[0]
            assert all(int(k) % 4 == pid for k in np.asarray(k_arr))
        sched.shutdown()

    def test_generic_payload_rejected_for_device_op(self):
        from asyncframework_tpu.engine.scheduler import JobScheduler

        sched = JobScheduler(num_workers=2)
        ds = DistributedDataset.from_list(sched, [(1, 2.0), (1, 3.0)])
        with pytest.raises(ValueError, match="from_array_pairs"):
            ds.reduce_by_key("sum")
        sched.shutdown()

    def test_auto_dispatch_picks_host_on_cpu_backend(self, monkeypatch):
        """The VERDICT r4 #2 dispatch rule: `auto` routes by backend --
        this rig's backend is CPU, so the vectorized host path must run
        (the measured winner there; the device path wins only with a real
        accelerator behind it)."""
        import jax

        from asyncframework_tpu.engine.scheduler import JobScheduler
        from asyncframework_tpu.ops import shuffle as shuffle_mod

        called = {}
        real = shuffle_mod.host_reduce_by_key

        def spy(parts, op="sum"):
            called["host"] = True
            return real(parts, op=op)

        monkeypatch.setattr(shuffle_mod, "host_reduce_by_key", spy)
        assert jax.default_backend() == "cpu"  # the rig this rule encodes
        sched = JobScheduler(num_workers=2)
        blocks = self._word_count_data(1000, 50, 2)
        ds = DistributedDataset.from_array_pairs(sched, blocks)
        ds.reduce_by_key("sum")
        sched.shutdown()
        assert called.get("host") is True

    def test_conf_forces_device_plane(self, monkeypatch):
        from asyncframework_tpu.conf import AsyncConf, set_global_conf
        from asyncframework_tpu.engine.scheduler import JobScheduler
        from asyncframework_tpu.ops import shuffle as shuffle_mod

        called = {}
        real = shuffle_mod.device_reduce_by_key

        def spy(parts, op="sum", devices=None, distinct_hint=None):
            called["device"] = True
            return real(parts, op=op, devices=devices,
                        distinct_hint=distinct_hint)

        monkeypatch.setattr(shuffle_mod, "device_reduce_by_key", spy)
        set_global_conf(AsyncConf({"async.shuffle.data.plane": "device"}))
        try:
            sched = JobScheduler(num_workers=2)
            blocks = self._word_count_data(1000, 50, 2)
            ds = DistributedDataset.from_array_pairs(sched, blocks)
            ds.reduce_by_key("sum")
            sched.shutdown()
        finally:
            set_global_conf(None)
        assert called.get("device") is True

    def test_host_vectorized_function_oracle(self):
        from asyncframework_tpu.ops.shuffle import host_reduce_by_key

        rs = np.random.default_rng(9)
        parts = {
            w: (rs.integers(0, 97, size=333).astype(np.int64),
                rs.normal(size=333).astype(np.float32))
            for w in range(3)
        }
        for op, npop in (("sum", np.add.reduce), ("max", np.maximum.reduce),
                         ("min", np.minimum.reduce)):
            out = host_reduce_by_key(parts, op=op)
            want = {}
            for w in parts:
                for k, v in zip(*parts[w]):
                    want.setdefault(int(k), []).append(float(v))
            got = {}
            for pid, (ks, vs) in out.items():
                for k, v in zip(ks, vs):
                    assert int(k) % 3 == pid
                    got[int(k)] = float(v)
            assert got.keys() == want.keys()
            for k in want:
                assert got[k] == pytest.approx(npop(want[k]), rel=1e-4), (
                    k, op,
                )

    def test_int64_sums_exact_beyond_2p53(self):
        """ISSUE 1 satellite: the bincount path summed via float64 weights,
        silently rounding integer totals past 2^53.  At the 2^60 boundary
        the sum must be bit-exact (int64 accumulation via np.add.at)."""
        from asyncframework_tpu.ops.shuffle import host_reduce_by_key

        big = np.int64(2**60 + 1)
        keys = np.asarray([0, 0, 1], np.int64)
        vals = np.asarray([big, big, 5], np.int64)
        out = host_reduce_by_key({0: (keys, vals)}, op="sum")
        got = {int(k): int(v) for k, v in zip(*out[0])}
        # 2^61 + 2 is NOT float64-representable; exact accumulation is
        assert got == {0: 2**61 + 2, 1: 5}
        # sparse keyspace (sort + reduceat route) stays exact too
        keys2 = np.asarray([2**40, 2**40, 7], np.int64)
        out2 = host_reduce_by_key({0: (keys2, vals)}, op="sum")
        got2 = {int(k): int(v) for k, v in zip(*out2[0])}
        assert got2 == {2**40: 2**61 + 2, 7: 5}

    def test_host_vectorized_sparse_keyspace_uses_sort_path(self):
        # keys sparse in a huge range: bincount would explode; the sort +
        # reduceat route must produce identical results
        from asyncframework_tpu.ops.shuffle import host_reduce_by_key

        keys = np.asarray([2**40, 5, 2**40, 7, 5], np.int64)
        vals = np.asarray([1., 2., 3., 4., 5.], np.float32)
        out = host_reduce_by_key({0: (keys, vals)}, op="sum")
        got = {int(k): float(v) for k, v in zip(*out[0])}
        assert got == {2**40: 4.0, 5: 7.0, 7: 4.0}

    @pytest.mark.slow
    def test_ten_million_pair_wordcount_measured(self):
        """VERDICT r4 #2's measured record for THIS rig (CPU backend, no
        TPU): the vectorized host plane must beat the driver-routed dict
        path by a wide margin on the 10M-pair wordcount; the device plane's
        numbers (emulated collective) are printed for the record.  The
        on-chip rematch stays armed in the probe loop."""
        import time as _time

        from asyncframework_tpu.engine.scheduler import JobScheduler

        n, vocab, parts_n = 10_000_000, 200_000, 8
        blocks = self._word_count_data(n, vocab, parts_n, seed=1)

        sched = JobScheduler(num_workers=parts_n)
        ds = DistributedDataset.from_array_pairs(sched, blocks)
        t0 = _time.monotonic()
        host_vec = ds.reduce_by_key("sum")  # auto -> host on this rig
        host_rows = host_vec.collect()
        t_hostvec = _time.monotonic() - t0
        sched.shutdown()

        # driver-routed dict path on a 1/10 sample (full 10M takes ~9s;
        # the sample keeps the suite fast and the scaling is linear)
        sample = n // 10
        pairs_list = [
            (int(k), float(v))
            for w in range(parts_n)
            for k, v in zip(blocks[w][0][: sample // parts_n],
                            blocks[w][1][: sample // parts_n])
        ]
        sched2 = JobScheduler(num_workers=parts_n)
        hd = DistributedDataset.from_list(sched2, pairs_list)
        t0 = _time.monotonic()
        hd.reduce_by_key(lambda a, b: a + b).collect()
        t_dict_sample = _time.monotonic() - t0
        sched2.shutdown()
        t_dict_est = t_dict_sample * (n / sample)

        total = sum(
            float(np.asarray(v).sum()) for _k, v in host_rows
        )
        assert total == pytest.approx(float(n), rel=1e-6)
        print(f"\n# 10M-pair wordcount: host-vectorized {t_hostvec:.2f}s; "
              f"driver dicts ~{t_dict_est:.1f}s (measured {t_dict_sample:.2f}s"
              f" on {sample} pairs); speedup {t_dict_est / t_hostvec:.1f}x")
        assert t_hostvec * 2 < t_dict_est

    def test_uneven_partitions_and_empty(self, plane):
        from asyncframework_tpu.engine.scheduler import JobScheduler

        sched = JobScheduler(num_workers=3)
        blocks = {
            0: (np.asarray([5, 5, 7], np.int32),
                np.asarray([1., 2., 3.], np.float32)),
            1: (np.asarray([7], np.int32), np.asarray([10.], np.float32)),
            2: (np.asarray([], np.int32), np.asarray([], np.float32)),
        }
        ds = DistributedDataset.from_array_pairs(sched, blocks)
        got = self._merged(ds.reduce_by_key("sum"))
        sched.shutdown()
        assert got == {5: 3.0, 7: 13.0}
