"""AZ1 native codec tests: round trip, cross-backend interop, hostile
input, ratio sanity, WAL integration (native-component parity: the
reference's lz4/snappy/zstd JNI codecs, CompressionCodec.scala)."""

import numpy as np
import pytest

from asyncframework_tpu.utils import codec

NATIVE_OK = codec._native_lib() is not None

needs_native = pytest.mark.skipif(
    not NATIVE_OK, reason="native codec not built"
)

BACKENDS = ["python", pytest.param("native", marks=needs_native)]


def payloads():
    rs = np.random.default_rng(0)
    return {
        "empty": b"",
        "tiny": b"abc",
        "repetitive": b"spark " * 2000,
        "rle": b"\x00" * 10_000,
        "random": rs.integers(0, 256, 50_000, dtype=np.uint8).tobytes(),
        "structured": b"".join(
            f"worker={i % 8} staleness={i % 5}\n".encode() for i in range(3000)
        ),
    }


class TestRoundTrip:
    @pytest.mark.parametrize("backend", BACKENDS)
    @pytest.mark.parametrize("name", list(payloads()))
    def test_round_trip(self, backend, name):
        data = payloads()[name]
        blob = codec.compress(data, backend=backend)
        assert codec.decompress(blob, backend=backend) == data

    @pytest.mark.parametrize("name", list(payloads()))
    @needs_native
    def test_cross_backend_interop(self, name):
        data = payloads()[name]
        # both directions: the formats must be byte-compatible
        assert codec.decompress(
            codec.compress(data, backend="native"), backend="python"
        ) == data
        assert codec.decompress(
            codec.compress(data, backend="python"), backend="native"
        ) == data

    def test_compresses_redundancy(self):
        data = payloads()["structured"]
        blob = codec.compress(data, backend="python")
        assert len(blob) < len(data) // 3  # >3x on log-like text
        rle = codec.compress(payloads()["rle"], backend="python")
        assert len(rle) < 600  # ~20x minimum on constant runs

    def test_random_data_bounded_expansion(self):
        data = payloads()["random"]
        blob = codec.compress(data, backend="python")
        assert len(blob) <= codec.max_compressed_size(len(data))


class TestHostileInput:
    @pytest.mark.parametrize("backend", BACKENDS)
    def test_corrupt_blocks_rejected(self, backend):
        good = codec.compress(b"hello world, hello world, hello", "python")
        cases = [
            good[:3],                       # truncated header
            good[:-1],                      # truncated tail
            good + b"x",                    # trailing garbage
            good[:4],                       # tokens missing entirely
            b"\xff\xff\xff\x7f" + b"\x01a",  # implausible raw length
        ]
        # bad offset: match token referencing before output start
        bad_offset = (8).to_bytes(4, "little") + bytes([0x80, 0xFF, 0xFF])
        cases.append(bad_offset)
        for c in cases:
            with pytest.raises(ValueError):
                codec.decompress(c, backend=backend)

    @pytest.mark.parametrize("backend", BACKENDS)
    @pytest.mark.slow
    def test_fuzz_never_crashes(self, backend):
        rs = np.random.default_rng(1)
        for _ in range(200):
            n = int(rs.integers(0, 200))
            junk = rs.integers(0, 256, n, dtype=np.uint8).tobytes()
            try:
                codec.decompress(junk, backend=backend)
            except ValueError:
                pass  # rejection is the expected outcome


class TestWALIntegration:
    def test_compressed_wal_round_trip(self, tmp_path):
        from asyncframework_tpu.streaming import WriteAheadLog

        p = tmp_path / "wal.az1"
        batch = np.tile(np.arange(64, dtype=np.float32), 100)
        with WriteAheadLog(p, compress=True) as wal:
            wal.append(100, batch)
            wal.append(200, {"rows": [1, 2, 3]})
        # a reader without the flag still replays (flag rides the record)
        with WriteAheadLog(p) as wal2:
            got = list(wal2.replay())
        assert got[0][0] == 100
        np.testing.assert_array_equal(got[0][1], batch)
        assert got[1][1] == {"rows": [1, 2, 3]}

    def test_compression_shrinks_wal(self, tmp_path):
        from asyncframework_tpu.streaming import WriteAheadLog

        batch = np.zeros(4096, np.float32)
        with WriteAheadLog(tmp_path / "plain") as w1:
            w1.append(1, batch)
        with WriteAheadLog(tmp_path / "comp", compress=True) as w2:
            w2.append(1, batch)
        assert (tmp_path / "comp").stat().st_size < \
            (tmp_path / "plain").stat().st_size // 4

    def test_torn_compressed_tail_truncated(self, tmp_path):
        from asyncframework_tpu.streaming import WriteAheadLog

        p = tmp_path / "torn"
        with WriteAheadLog(p, compress=True) as wal:
            wal.append(1, np.arange(100, dtype=np.float32))
        with open(p, "ab") as f:  # torn compressed record
            f.write((0x80000000 | 50).to_bytes(4, "little") + b"short")
        with WriteAheadLog(p, compress=True) as wal2:
            assert len(list(wal2.replay())) == 1
            wal2.append(2, np.arange(3, dtype=np.float32))
            assert len(list(wal2.replay())) == 2
