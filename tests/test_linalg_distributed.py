"""Distributed matrices: RowMatrix / IndexedRowMatrix / CoordinateMatrix /
BlockMatrix vs numpy oracles, single-device and over the 8-device mesh.

Reference parity targets: ``mllib/.../linalg/distributed/RowMatrix.scala``
(gramian, covariance, SVD :493, columnSimilarities, tallSkinnyQR),
``IndexedRowMatrix.scala``, ``CoordinateMatrix.scala``, ``BlockMatrix.scala``.
"""

import numpy as np
import pytest

from asyncframework_tpu.ml import (
    BlockMatrix,
    CoordinateMatrix,
    IndexedRowMatrix,
    RowMatrix,
)
from asyncframework_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def A():
    rs = np.random.default_rng(3)
    return rs.normal(size=(256, 12)).astype(np.float32)


@pytest.fixture(scope="module")
def mesh8(devices8):
    return make_mesh(8, devices=devices8)


class TestRowMatrix:
    def test_gramian_matches_numpy(self, A):
        g = np.asarray(RowMatrix(A).compute_gramian())
        np.testing.assert_allclose(g, A.T @ A, rtol=2e-4, atol=1e-3)

    def test_gramian_mesh_equals_single(self, A, mesh8):
        g1 = np.asarray(RowMatrix(A).compute_gramian())
        g8 = np.asarray(RowMatrix(A, mesh8).compute_gramian())
        np.testing.assert_allclose(g8, g1, rtol=1e-5, atol=1e-4)

    def test_covariance(self, A, mesh8):
        cov = np.asarray(RowMatrix(A, mesh8).compute_covariance())
        np.testing.assert_allclose(
            cov, np.cov(A, rowvar=False), rtol=2e-3, atol=2e-3
        )

    def test_column_summary(self, A, mesh8):
        st = RowMatrix(A, mesh8).compute_column_summary_statistics()
        np.testing.assert_allclose(
            np.asarray(st.mean), A.mean(0), rtol=1e-4, atol=1e-4
        )

    def test_svd_reconstructs(self, A):
        U, s, V = RowMatrix(A).compute_svd(12)
        rec = np.asarray(U) @ np.diag(s) @ np.asarray(V).T
        np.testing.assert_allclose(rec, A, rtol=2e-2, atol=2e-2)
        s_np = np.linalg.svd(A, compute_uv=False)
        np.testing.assert_allclose(s, s_np[: len(s)], rtol=1e-2)

    def test_multiply(self, A, mesh8):
        B = np.random.default_rng(4).normal(size=(12, 5)).astype(np.float32)
        out = np.asarray(RowMatrix(A, mesh8).multiply(B).X)
        np.testing.assert_allclose(out, A @ B, rtol=2e-4, atol=1e-3)

    def test_column_similarities(self, A):
        sims = np.asarray(RowMatrix(A).column_similarities())
        An = A / np.linalg.norm(A, axis=0, keepdims=True)
        want = np.triu(An.T @ An, k=1)
        np.testing.assert_allclose(sims, want, rtol=2e-3, atol=2e-3)
        assert np.all(np.tril(sims) == 0)

    @pytest.mark.parametrize("use_mesh", [False, True])
    def test_tall_skinny_qr(self, A, mesh8, use_mesh):
        rm = RowMatrix(A, mesh8 if use_mesh else None)
        Q, R = rm.tall_skinny_qr()
        Qh = np.asarray(Q.X)
        Rh = np.asarray(R)
        # factorization reproduces A, R upper-triangular, Q orthonormal
        np.testing.assert_allclose(Qh @ Rh, A, rtol=2e-3, atol=2e-3)
        assert np.allclose(Rh, np.triu(Rh))
        np.testing.assert_allclose(
            Qh.T @ Qh, np.eye(A.shape[1]), rtol=1e-3, atol=1e-3
        )
        assert np.all(np.diag(Rh) >= 0)

    def test_tsqr_mesh_matches_single(self, A, mesh8):
        _, R1 = RowMatrix(A).tall_skinny_qr()
        _, R8 = RowMatrix(A, mesh8).tall_skinny_qr()
        np.testing.assert_allclose(
            np.asarray(R8), np.asarray(R1), rtol=2e-3, atol=2e-3
        )


class TestIndexedRowMatrix:
    def test_roundtrip_and_multiply(self, A):
        idx = np.arange(A.shape[0])[::-1].copy()
        m = IndexedRowMatrix(idx, A)
        assert m.num_rows() == A.shape[0]
        B = np.eye(12, dtype=np.float32) * 2.0
        out = m.multiply(B)
        np.testing.assert_allclose(np.asarray(out.X), A * 2.0, rtol=1e-5)
        np.testing.assert_array_equal(out.indices, idx)

    def test_to_coordinate(self):
        X = np.array([[1.0, 0.0], [0.0, 3.0]], np.float32)
        cm = IndexedRowMatrix(np.array([5, 2]), X).to_coordinate_matrix()
        dense = np.asarray(cm.to_local())
        assert dense.shape == (6, 2)
        assert dense[5, 0] == 1.0 and dense[2, 1] == 3.0


class TestCoordinateMatrix:
    def test_to_local_sums_duplicates(self):
        cm = CoordinateMatrix(
            [0, 0, 1], [1, 1, 0], [2.0, 3.0, 4.0], shape=(2, 2)
        )
        dense = np.asarray(cm.to_local())
        assert dense[0, 1] == 5.0 and dense[1, 0] == 4.0

    def test_transpose(self):
        cm = CoordinateMatrix([0, 1], [1, 0], [2.0, 4.0], shape=(2, 3))
        t = cm.transpose()
        assert t.shape == (3, 2)
        assert np.asarray(t.to_local())[1, 0] == 2.0

    def test_to_block_matrix(self):
        rs = np.random.default_rng(5)
        dense = (rs.random((7, 9)) < 0.3) * rs.normal(size=(7, 9))
        r, c = np.nonzero(dense)
        cm = CoordinateMatrix(r, c, dense[r, c], shape=(7, 9))
        bm = cm.to_block_matrix(block_size=4)
        np.testing.assert_allclose(
            bm.to_local(), dense.astype(np.float32), rtol=1e-5, atol=1e-6
        )


class TestBlockMatrix:
    def test_multiply_matches_numpy(self):
        rs = np.random.default_rng(6)
        A = rs.normal(size=(37, 23)).astype(np.float32)
        B = rs.normal(size=(23, 31)).astype(np.float32)
        bm = BlockMatrix.from_dense(A, block_size=8)
        bn = BlockMatrix.from_dense(B, block_size=8)
        C = bm.multiply(bn)
        assert C.shape == (37, 31)
        np.testing.assert_allclose(C.to_local(), A @ B, rtol=2e-4, atol=2e-3)

    def test_add_and_transpose(self):
        rs = np.random.default_rng(7)
        A = rs.normal(size=(10, 6)).astype(np.float32)
        bm = BlockMatrix.from_dense(A, block_size=4)
        np.testing.assert_allclose(
            bm.add(bm).to_local(), 2 * A, rtol=1e-6
        )
        np.testing.assert_allclose(bm.transpose().to_local(), A.T, rtol=1e-6)

    def test_shape_mismatch_raises(self):
        a = BlockMatrix.from_dense(np.zeros((4, 4), np.float32), 2)
        b = BlockMatrix.from_dense(np.zeros((5, 4), np.float32), 2)
        with pytest.raises(ValueError):
            a.multiply(b)
        with pytest.raises(ValueError):
            a.add(b)
