"""Cluster observer + crash flight recorder (ISSUE 14).

The correctness spine:

- the collector turns thirteen PRs of per-process telemetry into ONE
  queryable system: discovery (static endpoints, the active ShardGroup's
  pre-assigned telemetry ports, supervisor membership carrying HELLO
  ``mport``), scrapes over the net/ retry plane, per-run per-role
  compacted history that outlives processes, and cross-role derived
  signals (straggler scores vs the peer median, merge-queue depth vs
  push rate, fleet freshness);
- the flight recorder's dump is at most one flush stale, so even an
  uncatchable SIGKILL leaves a post-mortem whose last events straddle
  the kill and whose push ledger checks out against the PS-side
  accepted_by_wid view (the chaos rider: every ``bin/chaos_sweep.py``
  seed SIGKILLs a worker child at a seeded point and harvests);
- THE acceptance (real processes): 2 workers + a 2-shard PS group + a
  serving replica under a seeded chaos schedule -- the run-history
  store reconstructs per-role throughput/staleness series ACROSS a
  shard failover, the straggler score flags the DELAY-injected worker,
  and the SIGKILLed worker's flight dump is harvested non-empty.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu import conf as conf_mod
from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.metrics import flightrec, live, observer, timeseries
from asyncframework_tpu.metrics.live import LiveUIServer
from asyncframework_tpu.metrics.observer import (
    ClusterObserver,
    RoleTarget,
    RunHistoryStore,
    parse_endpoints,
    straggler_scores,
)
from asyncframework_tpu.metrics.top import render_fleet
from asyncframework_tpu.net import faults, reset_net_totals
from asyncframework_tpu.net.retry import reset_breakers
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel import shardgroup as sg
from asyncframework_tpu.parallel import supervisor as sup_mod
from asyncframework_tpu.solvers import SolverConfig

pytestmark = pytest.mark.observer

CHILD = Path(__file__).parent / "ps_dcn_child.py"
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


@pytest.fixture(autouse=True)
def _clean_state():
    conf = AsyncConf()
    conf.set("async.metrics.interval.s", 0.25)
    set_global_conf(conf)
    reset_net_totals()
    reset_breakers()
    observer.reset_observer_totals()
    flightrec.reset_flight_totals()
    flightrec.uninstall()
    yield
    flightrec.uninstall()
    timeseries.stop_sampler()
    set_global_conf(None)


# ------------------------------------------------------------- pure units
class TestParseEndpoints:
    def test_grammar_forms(self):
        ts = parse_endpoints(
            "ps=ps@127.0.0.1:1234; w0=worker@h:2345, h:999 ;; "
        )
        assert [(t.name, t.role) for t in ts] == [
            ("ps", "ps"), ("w0", "worker"), ("process", "process")]
        assert ts[0].url == "http://127.0.0.1:1234"
        assert ts[2].url == "http://h:999"

    def test_role_without_name_names_the_target(self):
        (t,) = parse_endpoints("worker@h:1")
        assert t.name == "worker" and t.role == "worker"


class TestStragglerScores:
    def test_flags_outlier_vs_peer_median(self):
        out = straggler_scores(
            {"0": {"interval_ms": 10.0}, "1": {"interval_ms": 10.0},
             "2": {"interval_ms": 100.0}}, factor=2.5)
        assert out["2"]["flagged"] and out["2"]["score"] == 10.0
        assert not out["0"]["flagged"] and not out["1"]["flagged"]

    def test_two_worker_cohort_still_flags(self):
        """Peer-median (excluding self): an inclusive median would cap
        every 2-worker ratio below 2 -- a 10x straggler must flag."""
        out = straggler_scores(
            {"0": {"interval_ms": 10.0}, "1": {"interval_ms": 100.0}},
            factor=2.5)
        assert out["1"]["flagged"] and out["1"]["score"] == 10.0

    def test_single_worker_and_junk_dims_score_none(self):
        out = straggler_scores({"0": {"interval_ms": 10.0}})
        assert out["0"]["score"] is None and not out["0"]["flagged"]
        out = straggler_scores(
            {"0": {"interval_ms": "x"}, "1": {"other": 1.0}})
        assert all(v["score"] is None for v in out.values())

    def test_max_over_dims_wins_and_staleness_is_smoothed(self):
        out = straggler_scores(
            {"0": {"interval_ms": 10.0, "staleness": 1.0},
             "1": {"interval_ms": 10.0, "staleness": 28.0}}, factor=2.5)
        # staleness rides +2 additive smoothing: (28+2)/(1+2) = 10
        assert out["1"]["score"] == 10.0
        assert out["1"]["dims"]["staleness"] == 10.0
        # healthy small-integer staleness jitter (3 vs 1) stays calm:
        # (3+2)/(1+2) < 2.5 -- the noise that must never flag
        calm = straggler_scores(
            {"0": {"staleness": 1.0}, "1": {"staleness": 1.0},
             "2": {"staleness": 3.0}}, factor=2.5)
        assert not calm["2"]["flagged"]


class TestDefaultFleetRules:
    def test_default_rules_include_observer_family(self):
        from asyncframework_tpu.metrics.slo import parse_rules

        rules = parse_rules(str(AsyncConf().get(conf_mod.SLO_RULES)))
        by_name = {r.name: r for r in rules}
        assert "fleet_stragglers" in by_name
        assert by_name["fleet_stragglers"].series == \
            "observer.straggler_score"
        assert by_name["fleet_stragglers"].unless_series == \
            "observer.fleet_done"
        assert "fleet_freshness" in by_name and "fleet_roles" in by_name

    def test_series_families_declares_observer_and_dynamics(self):
        from asyncframework_tpu.metrics import registry

        fams = registry.series_families()
        for name in ("observer", "flight", "ps", "ps_shards", "serving",
                     "trace", "convergence"):
            assert name in fams, name


# -------------------------------------------------------- run-history store
class TestRunHistoryStore:
    def test_compaction_spans_whole_run_at_bounded_size(self):
        h = RunHistoryStore(None, "r", points=32)
        for i in range(10_000):
            h.record("ps", "ps.accepted", float(i), float(2 * i))
        pts = h.series_of("ps")["ps.accepted"]
        assert len(pts) < 64  # bounded
        assert pts[0][0] == 0.0  # the start survives compaction
        assert pts[-1][0] > 9000.0  # and the tail is recent

    def test_persist_load_roundtrip_and_index(self, tmp_path):
        root = tmp_path / "hist"
        h = RunHistoryStore(str(root), "runA", points=32)
        h.note_role("ps", "ps", "http://x:1")
        for i in range(50):
            h.record("ps", "ps.accepted", float(i), float(i))
        dump = {"role": "worker-0", "dumped_s": 1.0,
                "events": [{"t": 1.0, "kind": "push"}]}
        assert h.harvest(dump, source="flight-worker-0-1.json")
        # same dumped_s = stale copy: not re-harvested
        assert not h.harvest(dict(dump), source="flight-worker-0-1.json")
        # fresher overwrite of the same file IS re-harvested
        assert h.harvest(dict(dump, dumped_s=2.0),
                         source="flight-worker-0-1.json")
        rd = h.persist()
        run = observer.load_run(rd)
        assert run["meta"]["run_id"] == "runA"
        assert run["roles"]["ps"]["series"]["ps.accepted"]
        assert list(run["flight"]) == ["flight-worker-0-1.json"]
        assert run["flight"]["flight-worker-0-1.json"]["dumped_s"] == 2.0
        assert observer.list_runs(str(root)) == [rd]
        # bin/async-history renders an index section over observer runs
        from asyncframework_tpu.metrics.history import build_history

        index = build_history(root)
        text = index.read_text()
        assert "Observer run history" in text and "runA" in text

    def test_memory_only_mode_never_writes(self):
        h = RunHistoryStore(None, "r")
        h.record("ps", "ps.accepted", 0.0, 1.0)
        assert h.persist() is None and h.run_dir is None

    def test_persist_skips_unchanged_flight_dumps(self, tmp_path):
        """Dirty tracking: an unchanged dump is not re-serialized on the
        next persist cycle (steady-state persist cost on a long run)."""
        h = RunHistoryStore(str(tmp_path), "r", points=32)
        h.harvest({"role": "w", "dumped_s": 1.0, "events": [{}]},
                  source="flight-w-1.json")
        rd = h.persist()
        dump_path = Path(rd) / "flight" / "flight-w-1.json"
        first_stat = dump_path.stat()
        time.sleep(0.05)
        h.record("ps", "ps.accepted", 0.0, 1.0)  # other state moves
        h.persist()
        assert dump_path.stat().st_mtime_ns == first_stat.st_mtime_ns
        # meta still lists the dump even on a no-rewrite cycle
        run = observer.load_run(rd)
        assert run["meta"]["flight_dumps"] == ["flight-w-1.json"]
        # a FRESHER harvest is re-written
        h.harvest({"role": "w", "dumped_s": 2.0, "events": [{}]},
                  source="flight-w-1.json")
        h.persist()
        assert dump_path.stat().st_mtime_ns != first_stat.st_mtime_ns

    def test_series_cap_counts_drops(self):
        h = RunHistoryStore(None, "r", points=16)
        h.MAX_SERIES_PER_ROLE = 4
        for i in range(10):
            h.record("ps", f"ps.k{i}", 0.0, 1.0)
        assert len(h.series_of("ps")) == 4
        assert h.series_dropped == 6


# ------------------------------------------------------------ flight recorder
class TestFlightRecorder:
    def test_ring_bounds_and_dump_roundtrip(self, tmp_path):
        rec = flightrec.FlightRecorder("w", str(tmp_path), capacity=16,
                                       flush_s=0.0)
        for i in range(40):
            rec.note("push", wid=0, n=i)
        path = rec.dump("manual")
        data = flightrec.load_dump(path)
        assert data["role"] == "w" and data["reason"] == "manual"
        assert len(data["events"]) == 16  # bounded
        assert data["dropped"] == 24 and data["seq"] == 40
        assert data["events"][-1]["n"] == 39  # newest survive

    def test_periodic_flush_and_counter_deltas(self, tmp_path):
        rec = flightrec.install("w", str(tmp_path), capacity=64,
                                flush_s=0.1)
        flightrec.note("push", wid=1, n=1)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if flightrec.scan_dumps(str(tmp_path)):
                break
            time.sleep(0.1)
        paths = flightrec.scan_dumps(str(tmp_path))
        assert paths, "periodic flush never wrote a dump"
        data = flightrec.load_dump(paths[0])
        kinds = {e["kind"] for e in data["events"]}
        assert "push" in kinds
        # our own flight meta-counters never feed the delta event (each
        # flush would otherwise generate the next flush's delta forever)
        for ev in data["events"]:
            if ev["kind"] == "counters":
                assert not any(k.startswith("flight.")
                               for k in ev["delta"])

    def test_install_from_conf_gating(self, tmp_path):
        conf = AsyncConf()
        set_global_conf(conf)
        assert flightrec.install_from_conf("w") is None  # dir empty = off
        conf.set("async.flight.dir", str(tmp_path))
        conf.set("async.flight.flush.s", 0.0)
        rec = flightrec.install_from_conf("w")
        assert rec is not None
        # idempotent: one process, one recorder identity
        assert flightrec.install_from_conf("other") is rec

    def test_note_is_noop_when_uninstalled(self):
        assert flightrec.recorder() is None
        flightrec.note("push", wid=0)  # must not raise
        assert flightrec.flight_totals()["notes"] == 0

    def test_harvest_skips_previous_runs_stale_dumps(self, tmp_path):
        """A collector restarted against yesterday's flight dir must not
        attribute yesterday's crashes to today's run: dumps last written
        long before the collector started are skipped (counted)."""
        stale = {"schema": 1, "role": "w", "pid": 1,
                 "dumped_s": time.time() - 3600.0,
                 "events": [{"t": 1.0, "kind": "push"}]}
        (tmp_path / "flight-w-1.json").write_text(json.dumps(stale))
        fresh = dict(stale, dumped_s=time.time(), pid=2)
        (tmp_path / "flight-w-2.json").write_text(json.dumps(fresh))
        obs = ClusterObserver(interval_s=0.0, history_dir="",
                              flight_dirs=[str(tmp_path)])
        assert obs.harvest_flight() == 1
        assert list(obs.history.flight_dumps()) == ["flight-w-2.json"]
        assert observer.observer_totals()["harvest_stale_skipped"] == 1

    def test_scan_ignores_foreign_files(self, tmp_path):
        (tmp_path / "flight-w-1.json").write_text("{}")  # no events key
        (tmp_path / "other.json").write_text("{}")
        paths = flightrec.scan_dumps(str(tmp_path))
        assert [os.path.basename(p) for p in paths] == ["flight-w-1.json"]
        with pytest.raises(ValueError):
            flightrec.load_dump(paths[0])


# -------------------------------------------------- status-section plumbing
class TestStatusSections:
    def test_register_appears_and_unregister_is_identity_gated(self):
        fn_a = lambda: {"a": 1}  # noqa: E731
        fn_b = lambda: {"b": 2}  # noqa: E731
        live.register_status_section("obs_test", fn_a)
        try:
            assert live.process_status()["obs_test"] == {"a": 1}
            live.register_status_section("obs_test", fn_b)  # last wins
            assert live.process_status()["obs_test"] == {"b": 2}
            live.unregister_status_section("obs_test", fn_a)  # stale: no-op
            assert live.process_status()["obs_test"] == {"b": 2}
        finally:
            live.unregister_status_section("obs_test")
        assert "obs_test" not in live.process_status()

    def test_raising_section_does_not_500_status(self):
        def bad():
            raise RuntimeError("boom")

        live.register_status_section("obs_bad", bad)
        try:
            status = live.process_status()
            assert "obs_bad" not in status and "counters" in status
        finally:
            live.unregister_status_section("obs_bad")


class TestDiscovery:
    def test_supervisor_membership_carries_mport(self):
        sup = sup_mod.ElasticSupervisor(2, dead_after_s=5.0).start()
        try:
            sup.register("proc-a", [0], pid=os.getpid(),
                         host="127.0.0.1", mport=12345)
            assert sup in sup_mod.active_supervisors()
            recs = {r["proc"]: r for r in sup.proc_records()}
            assert recs["proc-a"]["mport"] == 12345
            obs = ClusterObserver(interval_s=0.0, history_dir="")
            names = {t.name: t for t in obs.targets()}
            assert "worker-proc-a" in names
            assert names["worker-proc-a"].url == "http://127.0.0.1:12345"
            # "discovered" counts roles, not ticks: a second discovery
            # pass over the same membership bumps nothing
            n0 = observer.observer_totals()["discovered"]
            obs.targets()
            assert observer.observer_totals()["discovered"] == n0
        finally:
            sup.stop()
        assert sup not in sup_mod.active_supervisors()

    def test_span_only_worker_never_enters_wstats(self, devices8):
        """A booting worker's first piggybacked span must not mint a
        span-only stats entry (no accepted count -> it would bypass the
        straggler warm-up guard and flag on one EWMA sample)."""
        from asyncframework_tpu.metrics import trace as trace_mod

        cfg = _small_cfg(num_iterations=10)
        ps = ps_dcn.ParameterServer(cfg, 4, 32, device=devices8[0],
                                    port=0).start()
        try:
            span = trace_mod.Span(
                stage=trace_mod.COMPUTE, trace_id="t", span_id="s",
                parent_id=None, worker_id=3, model_version=0,
                start_ms=0.0, dur_ms=3000.0)
            ps._wstat_span(span)
            assert ps.worker_stats() == {}
            # once the drain created the entry, spans fold into it
            ps._wstat_merge(3, staleness=1, accepted=True)
            ps._wstat_span(span)
            assert "compute_ms" in ps.worker_stats()["3"]
        finally:
            ps.stop()

    def test_hello_advertises_local_telemetry_port(self, devices8):
        """End-to-end: a worker process serving telemetry HELLOs its
        mport; the PS supervisor records it."""
        cfg = SolverConfig(num_workers=2, num_iterations=10, gamma=0.5,
                           taw=2**31 - 1, batch_rate=0.5,
                           bucket_ratio=0.0, printer_freq=5, seed=42,
                           calibration_iters=10**9, run_timeout_s=30.0)
        sup = sup_mod.ElasticSupervisor(2, dead_after_s=30.0)
        ps = ps_dcn.ParameterServer(cfg, 4, 32, device=devices8[0],
                                    port=0, supervisor=sup).start()
        srv = LiveUIServer(None, port=0, role="worker").start()
        try:
            assert live.telemetry_port() == srv.port
            cl = ps_dcn.PSClient("127.0.0.1", ps.port)
            cl.hello("tele-proc", [0], pid=os.getpid())
            cl.bye()
            recs = {r["proc"]: r for r in sup.proc_records()}
            assert recs["tele-proc"]["mport"] == srv.port
        finally:
            srv.stop()
            ps.stop()

    def test_shardgroup_preassigns_telemetry_ports(self, tmp_path):
        cfg = SolverConfig(num_workers=2, num_iterations=10, gamma=0.5,
                           taw=2**31 - 1, batch_rate=0.5,
                           bucket_ratio=0.5, printer_freq=5, seed=42)
        group = sg.ShardGroup(cfg, 8, 64, 2, telemetry_ports="auto")
        targets = group.telemetry_targets()
        assert [t[0] for t in targets] == ["ps-shard-0", "ps-shard-1"]
        assert all(r == "ps" for (_n, r, _u) in targets)
        ports = {int(u.rsplit(":", 1)[1]) for (_n, _r, u) in targets}
        assert len(ports) == 2 and all(p > 0 for p in ports)
        env = group._child_env(0, 0)
        assert env["ASYNCTPU_ASYNC_METRICS_PORT"] == str(
            group.telemetry_ports[0])
        # a default group pins nothing and injects nothing
        plain = sg.ShardGroup(cfg, 8, 64, 2)
        assert plain.telemetry_targets() == []

    def test_standby_gets_own_port_for_promotion_handoff(self):
        """With standbys on, auto mode assigns each slot a SECOND port
        for its standby (two processes cannot share one bind) and
        injects it into standby spawns -- the port _promote() hands to
        the slot so the role's scrape URL follows the serving member
        instead of pointing at the dead primary forever."""
        cfg = SolverConfig(num_workers=2, num_iterations=10, gamma=0.5,
                           taw=2**31 - 1, batch_rate=0.5,
                           bucket_ratio=0.5, printer_freq=5, seed=42)
        group = sg.ShardGroup(cfg, 8, 64, 2, standbys=1,
                              telemetry_ports="auto",
                              conf_overlays={"async.fence.enabled": True,
                                             "async.ps.standby": 1})
        prim = set(group.telemetry_ports.values())
        sbs = set(group._standby_tports.values())
        assert len(prim) == 2 and len(sbs) == 2 and not (prim & sbs)
        env = group._child_env(1, 0, role="standby")
        assert env["ASYNCTPU_ASYNC_METRICS_PORT"] == str(
            group._standby_tports[1])


class TestRenderFleet:
    def test_render_fleet_pure(self):
        snap = {
            "roles": {
                "ps-shard-0": {"role": "ps", "up": True, "health": "ok",
                               "accepted": 120, "staleness": 3},
                "worker-w1": {"role": "worker", "up": False,
                              "errors": 4},
            },
            "derived": {"roles_up": 1, "roles_down": 1,
                        "push_rate": 42.5, "merge_queue_depth": 2,
                        "straggler_score": 3.2, "fleet_done": 0},
            "stragglers": {"1": {"score": 3.2,
                                 "dims": {"interval_ms": 3.2},
                                 "flagged": True}},
            "straggler_factor": 2.5,
            "history": {"run_id": "r1", "roles": {"ps-shard-0": {}},
                        "flight_dumps": ["flight-w.json"],
                        "run_dir": None},
        }
        text = render_fleet(snap, plain=True)
        assert "ps-shard-0" in text and "DOWN" in text
        assert "push_rate=42.5" in text and "straggler_max=3.20" in text
        assert "w1" in text and "<<" in text  # the flagged marker
        assert "flight_dumps=1" in text

    def test_async_top_observer_flag_renders_fleet(self):
        """--observer against a live collector's /api/status."""
        from asyncframework_tpu.metrics import top as top_mod

        obs = ClusterObserver(interval_s=0.0, history_dir="")
        obs.start()
        srv = LiveUIServer(None, port=0, role="observer").start()
        try:
            import io
            from contextlib import redirect_stdout

            buf = io.StringIO()
            with redirect_stdout(buf):
                rc = top_mod.main([f"--observer",
                                   f"127.0.0.1:{srv.port}",
                                   "--once", "--plain"])
            assert rc == 0
            assert "fleet view" in buf.getvalue()
        finally:
            srv.stop()
            obs.stop()


# ------------------------------------------------------ in-process collector
def _small_cfg(**kw):
    defaults = dict(
        num_workers=4, num_iterations=300, gamma=0.5, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.5, printer_freq=50, seed=42,
        calibration_iters=10, run_timeout_s=60.0, trace_sample=1.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


class TestCollectorInProcess:
    def test_scrape_folds_series_and_derives_signals(self, devices8):
        """One real PS run scraped over real HTTP: history series,
        per-worker stats, derived signals."""
        from asyncframework_tpu.data.sharded import ShardedDataset

        conf_mod.global_conf().set("async.trace.sample", 1.0)
        cfg = _small_cfg()
        d, n = 8, 256
        ds = ShardedDataset.generate_on_device(
            n, d, cfg.num_workers, devices=devices8, seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        srv = LiveUIServer(None, port=0, role="ps").start()
        obs = ClusterObserver(
            targets=[RoleTarget("ps", "ps",
                                f"http://127.0.0.1:{srv.port}")],
            interval_s=0.2, history_dir="", persist_s=0.0,
        ).start()
        try:
            shards = {w: ds.shard(w) for w in range(cfg.num_workers)}
            ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, list(range(cfg.num_workers)),
                shards, cfg, d, n, deadline_s=60.0)
            assert ps.wait_done(timeout_s=10.0)
            time.sleep(0.6)  # one sampler tick lands the final counters
            obs.scrape_once()
            snap = obs.fleet_snapshot()
            assert snap["roles"]["ps"]["up"]
            assert snap["roles"]["ps"]["accepted"] == cfg.num_iterations
            assert snap["derived"]["fleet_done"] == 1.0
            assert snap["derived"]["roles_up"] == 1.0
            # per-worker stats flowed PS -> /api/status -> scoring
            assert len(snap["stragglers"]) == cfg.num_workers
            wstats = ps.worker_stats()
            assert sum(st["accepted"] for st in wstats.values()) == \
                cfg.num_iterations
            for st in wstats.values():  # spans folded latency dims
                assert "compute_ms" in st and "rtt_ms" in st
            hist = obs.history.series_of("ps")
            assert "ps.accepted" in hist and "ps.queue_depth" in hist
            assert hist["up"][-1][1] == 1.0
            # the derived signals are recorded as a role too
            oh = obs.history.series_of("observer")
            assert "observer.roles_up" in oh
            # and the observer source feeds the process-global store
            timeseries.sample_once()
            assert timeseries.store().last("observer.roles_up") == 1.0
        finally:
            obs.stop()
            srv.stop()
            ps.stop()

    def test_derived_signals_follow_the_living_not_the_corpse(self):
        """A dead role's final status must not keep owning primary
        selection / fleet_done after a failover (white-box: inject a
        corpse with the largest ps.accepted next to a live primary)."""
        obs = ClusterObserver(interval_s=0.0, history_dir="")
        dead = {"timeseries": {"last": {"ps.accepted": 9999.0,
                                        "ps.done": 0.0,
                                        "ps.queue_depth": 50.0}}}
        live_st = {"timeseries": {"last": {"ps.accepted": 100.0,
                                           "ps.done": 1.0,
                                           "ps.queue_depth": 0.0}}}
        with obs._lock:
            obs._last_status = {"old-ps": dead, "new-ps": live_st}
            obs._target_state = {
                "old-ps": {"role": "ps", "up": False},
                "new-ps": {"role": "ps", "up": True},
            }
        obs._recompute_derived(time.time())
        d = obs.derived()
        # the LIVE primary's view wins: done=1, its queue depth
        assert d["fleet_done"] == 1.0
        assert d["merge_queue_depth"] == 0.0
        assert d["roles_down"] == 1.0

    def test_vanished_discovered_target_is_pruned(self):
        """A discovered target that discovery stops returning (e.g. a
        promotion moved the role to a new port) drops out of the fleet
        state instead of reading DOWN forever."""
        sup = sup_mod.ElasticSupervisor(1, dead_after_s=5.0).start()
        try:
            sup.register("p1", [0], pid=os.getpid(), host="127.0.0.1",
                         mport=19)
            obs = ClusterObserver(interval_s=0.0, history_dir="")
            obs.scrape_once()  # discovers worker-p1 (scrape fails; fine)
            assert "worker-p1" in obs.fleet_snapshot()["roles"]
        finally:
            sup.stop()
        obs.scrape_once()  # supervisor gone: target pruned
        assert "worker-p1" not in obs.fleet_snapshot()["roles"]

    def test_dead_target_counts_down_and_keeps_scraping(self):
        obs = ClusterObserver(
            targets=[RoleTarget("ghost", "worker",
                                "http://127.0.0.1:9")],
            interval_s=0.0, history_dir="")
        res = obs.scrape_once()
        assert res["ghost"]["ok"] is False
        snap = obs.fleet_snapshot()
        assert snap["roles"]["ghost"]["up"] is False
        assert snap["derived"]["roles_down"] == 1.0
        pts = obs.history.series_of("ghost")["up"]
        assert pts[-1][1] == 0.0
        assert observer.observer_totals()["scrape_errors"] >= 1


# ------------------------------------- chaos rider: flight harvest on kill
class TestFlightHarvestChaos:
    """Rides every bin/chaos_sweep.py seed: SIGKILL a worker child
    mid-run at a seeded point; the collector must harvest a dump whose
    last events straddle the kill and whose push ledger matches the
    PS-side accepted_by_wid view."""

    NW, D, N = 8, 24, 4096
    FLUSH_S = 0.2

    def _worker(self, port, wpid, tmp, flight_dir):
        env = dict(os.environ)
        env.update({
            "PS_ROLE": "worker", "PS_PORT": str(port),
            "PS_WORKER_ID": str(wpid), "PS_NUM_WORKER_PROCS": "2",
            "PS_NUM_ITER": "1000000", "PS_EVAL": "0",
            "JAX_PLATFORMS": "cpu",
            "ASYNCTPU_ASYNC_FLIGHT_DIR": flight_dir,
            "ASYNCTPU_ASYNC_FLIGHT_FLUSH_S": str(self.FLUSH_S),
            "PS_METRICS": "1",
            "ASYNCTPU_ASYNC_METRICS_PORT": "0",
        })
        return subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(tmp, f"w{wpid}.stderr.log"), "w"),
            text=True,
        )

    def test_sigkill_worker_harvests_straddling_dump(self, tmp_path,
                                                     devices8):
        flight_dir = str(tmp_path / "flight")
        cfg = SolverConfig(
            num_workers=self.NW, num_iterations=10**6, gamma=1.2,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.0,
            printer_freq=50, seed=42, calibration_iters=20,
            run_timeout_s=120.0,
        )
        ps = ps_dcn.ParameterServer(cfg, self.D, self.N,
                                    device=devices8[0], port=0).start()
        obs = ClusterObserver(interval_s=0.0, history_dir="",
                              flight_dirs=[flight_dir])
        workers = []
        try:
            workers = [
                self._worker(ps.port, 0, str(tmp_path), flight_dir),
                self._worker(ps.port, 1, str(tmp_path), flight_dir),
            ]
            # seeded kill point, gated on the VICTIM's own wids (the
            # even ones): the other child booting faster must not let
            # the kill land before the victim pushed anything -- the
            # dump needs a ledger to check
            kill_after = 40 + (CHAOS_SEED % 30)
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                victim_acc = sum(c for w, c in
                                 ps.accepted_by_wid.items()
                                 if w % 2 == 0)
                if victim_acc >= kill_after:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("run never reached the seeded kill point")
            # one flush cadence so the ledger reaches disk pre-kill
            time.sleep(2 * self.FLUSH_S)
            victim = workers[0]
            os.kill(victim.pid, signal.SIGKILL)
            t_kill = time.time()
            victim.wait(timeout=30.0)
            # let the survivors push on: the PS-side view must move PAST
            # the victim's frozen ledger without the dump moving with it
            time.sleep(1.0)
            assert obs.harvest_flight() >= 1, (
                f"no dump harvested from {flight_dir}: "
                f"{os.listdir(flight_dir) if os.path.isdir(flight_dir) else 'missing'}"
            )
            dumps = obs.history.flight_dumps()
            victim_dumps = [d for d in dumps.values()
                            if d.get("pid") == victim.pid]
            assert victim_dumps, f"harvested dumps: {list(dumps)}"
            dump = victim_dumps[0]
            events = dump["events"]
            assert events, "flight dump has no events"
            pushes = [e for e in events if e["kind"] == "push"]
            assert pushes, "no push breadcrumbs in the dump"
            # the dump STRADDLES the kill: its events end at most one
            # flush (+ scheduling slack) before the SIGKILL landed, and
            # span real time before it
            last_t = max(e["t"] for e in events)
            first_t = min(e["t"] for e in events)
            assert last_t <= t_kill + 0.5
            assert t_kill - last_t < 10 * self.FLUSH_S + 3.0, (
                f"dump went stale {t_kill - last_t:.2f}s before the kill"
            )
            assert first_t < last_t
            # the push ledger matches the PS-side view: the victim owned
            # the EVEN wids; for each, its last cumulative count must
            # not exceed what the PS accepted from that wid, and must be
            # within one flush window's worth of pushes of it
            by_wid = {}
            for e in pushes:
                by_wid[e["wid"]] = max(by_wid.get(e["wid"], 0), e["n"])
            assert by_wid, "push events carry no wids"
            assert all(w % 2 == 0 for w in by_wid)
            acc = ps.accepted_by_wid
            checked = 0
            for wid, n_dump in by_wid.items():
                ps_n = int(acc.get(wid, 0))
                assert n_dump <= ps_n + 1, (wid, n_dump, ps_n)
                assert ps_n - n_dump <= 200, (wid, n_dump, ps_n)
                checked += 1
            assert checked >= 1
        finally:
            for w in workers:
                if w.poll() is None:
                    w.kill()
            for w in workers:
                try:
                    w.wait(timeout=20.0)
                except subprocess.TimeoutExpired:
                    pass
            ps.stop()


# ----------------------------------------------- THE acceptance (real procs)
class TestObserverAcceptance:
    """Real OS processes end to end: a 2-shard PS group with
    pre-assigned telemetry ports, two worker processes (one
    DELAY-injected), an in-process serving replica, and one collector
    -- through a seeded SIGKILL of a shard child AND of a worker."""

    NW, D, N = 8, 24, 4096
    FLUSH_S = 0.2

    def _worker(self, port, wpid, tmp, flight_dir, delay_ms=0.0):
        env = dict(os.environ)
        env.update({
            "PS_ROLE": "worker", "PS_PORT": str(port),
            "PS_WORKER_ID": str(wpid), "PS_NUM_WORKER_PROCS": "2",
            "PS_NUM_ITER": "1000000", "PS_EVAL": "0",
            "JAX_PLATFORMS": "cpu",
            "PS_METRICS": "1",
            "ASYNCTPU_ASYNC_METRICS_PORT": "0",
            "ASYNCTPU_ASYNC_METRICS_INTERVAL_S": "0.25",
            "ASYNCTPU_ASYNC_TRACE_SAMPLE": "1",
            "ASYNCTPU_ASYNC_FLIGHT_DIR": flight_dir,
            "ASYNCTPU_ASYNC_FLIGHT_FLUSH_S": str(self.FLUSH_S),
        })
        if delay_ms > 0:
            sched = faults.FaultSchedule(seed=CHAOS_SEED)
            sched.add_delay("*", "PUSH", delay_ms, count=0)
            env["ASYNCTPU_ASYNC_NET_FAULT_SCHEDULE"] = sched.to_json()
        return subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(tmp, f"aw{wpid}.stderr.log"), "w"),
            text=True,
        )

    def _await_series(self, obs, role, key, pred, timeout_s, what):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            pts = obs.history.series_of(role).get(key) or []
            if pts and pred(pts):
                return pts
            time.sleep(0.1)
        pytest.fail(f"{what} (role={role} key={key})")

    def test_acceptance_failover_straggler_flight(self, tmp_path):
        flight_dir = str(tmp_path / "flight")
        hist_root = str(tmp_path / "history")
        cfg = SolverConfig(
            num_workers=self.NW, num_iterations=10**6, gamma=1.2,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
            printer_freq=50, seed=42, calibration_iters=20,
            run_timeout_s=240.0,
        )
        group = sg.ShardGroup(
            cfg, self.D, self.N, 2, checkpoint_dir=str(tmp_path),
            worker_procs=2, dead_after_s=1.0, check_interval_s=0.2,
            stderr_dir=str(tmp_path),
            conf_overlays={"async.metrics.interval.s": 0.25},
            telemetry_ports="auto",
        ).start()
        workers = []
        rep = None
        rep_srv = None
        obs = None
        try:
            port0 = group.port_of(0)
            # the group is the ACTIVE group in this process: the
            # collector discovers its telemetry targets by itself
            obs = ClusterObserver(
                interval_s=0.25, history_dir=hist_root,
                persist_s=1.0, flight_dirs=[flight_dir],
            )
            names = {t.name for t in obs.targets()}
            assert {"ps-shard-0", "ps-shard-1"} <= names
            # serving replica (in-process) + its scrape endpoint
            from asyncframework_tpu.serving.replica import ModelReplica

            rep = ModelReplica("127.0.0.1", port0, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=0.2).start()
            rep_srv = LiveUIServer(None, port=0, role="replica").start()
            obs.add_targets([RoleTarget(
                "replica-0", "replica",
                f"http://127.0.0.1:{rep_srv.port}")])
            obs.start()
            # workers: child 1 is the DELAY-injected straggler (every
            # PUSH pays the seeded extra latency)
            workers = [
                self._worker(port0, 0, str(tmp_path), flight_dir),
                self._worker(port0, 1, str(tmp_path), flight_dir,
                             delay_ms=150.0),
            ]
            for w in workers:
                hello = json.loads(w.stdout.readline())
                assert hello.get("metrics_port"), hello
                obs.add_targets([RoleTarget(
                    f"worker-{w.pid}", "worker",
                    f"http://127.0.0.1:{hello['metrics_port']}")])

            # phase 1: training flows -- the history store sees shard
            # throughput series from BOTH shards
            kill_after = 60 + (CHAOS_SEED % 50)
            for shard_role in ("ps-shard-0", "ps-shard-1"):
                self._await_series(
                    obs, shard_role, "ps.accepted",
                    lambda pts: pts[-1][1] >= kill_after, 120.0,
                    "shard never reached the seeded kill threshold")

            # phase 2: straggler scoring flags the DELAY-injected
            # worker's wids (child 1 serves the ODD wids).  The window
            # bound: once per-worker stats exist, one scrape recomputes
            # the scores -- so the flag lands within seconds, not a
            # convergence horizon.
            deadline = time.monotonic() + 60.0
            flagged = set()
            stable = False
            while time.monotonic() < deadline:
                snap = obs.fleet_snapshot()
                flagged = {int(w) for w, s in snap["stragglers"].items()
                           if s.get("flagged")}
                # accept the verdict once it points at the injected
                # cohort only (a single EWMA spike can transiently flag
                # a healthy worker during boot; the steady state must
                # name the DELAYed one) -- once eligible stats exist,
                # each scrape recomputes the scores, so this lands
                # within one scrape window of the cohort warming up
                if flagged and flagged <= {1, 3, 5, 7}:
                    stable = True
                    break
                time.sleep(0.25)
            assert stable, (
                f"straggler verdict never settled on the DELAY-injected "
                f"workers; last flagged={flagged} "
                f"stragglers={snap['stragglers']}")
            assert snap["derived"]["straggler_score"] >= 2.5
            assert snap["derived"].get("push_rate") is not None

            # phase 3: SIGKILL shard 1 -> the controller relaunches it
            # from its checkpoint on the SAME wire + telemetry ports;
            # the history store reconstructs the series ACROSS the
            # failover
            os.kill(group.pid_of(1), signal.SIGKILL)
            t_kill_shard = time.time()
            deadline = time.monotonic() + 90.0
            while time.monotonic() < deadline:
                if group.restarts_of(1) >= 1:
                    break
                time.sleep(0.1)
            else:
                pytest.fail("shard 1 was never relaunched")
            # scrapes resume on the same URL: post-restart samples land
            self._await_series(
                obs, "ps-shard-1", "ps.accepted",
                lambda pts: pts[-1][0] > t_kill_shard + 1.0
                and pts[-1][1] > 0, 120.0,
                "shard 1 series never resumed after the failover")
            acc_pts = obs.history.series_of("ps-shard-1")["ps.accepted"]
            up_pts = obs.history.series_of("ps-shard-1")["up"]
            assert acc_pts[0][0] < t_kill_shard, \
                "history lost the pre-failover samples"
            assert any(t > t_kill_shard for (t, _v) in acc_pts)
            assert any(v == 0.0 for (_t, v) in up_pts), \
                "the down window never registered"
            stale_pts = obs.history.series_of(
                "ps-shard-1").get("ps.max_staleness")
            assert stale_pts, "no staleness series for the shard"

            # phase 4: SIGKILL worker 0 -> its flight dump is harvested
            # non-empty with push breadcrumbs
            victim = workers[0]
            os.kill(victim.pid, signal.SIGKILL)
            t_kill_w = time.time()
            victim.wait(timeout=30.0)
            deadline = time.monotonic() + 30.0
            dump = None
            while time.monotonic() < deadline:
                obs.harvest_flight()
                for d in obs.history.flight_dumps().values():
                    if d.get("pid") == victim.pid:
                        dump = d
                        break
                if dump is not None:
                    break
                time.sleep(0.2)
            assert dump is not None, "victim's flight dump not harvested"
            pushes = [e for e in dump["events"] if e["kind"] == "push"]
            assert pushes, "harvested dump carries no push breadcrumbs"
            last_t = max(e["t"] for e in dump["events"])
            assert t_kill_w - last_t < 10 * self.FLUSH_S + 3.0

            # teardown-time durability: everything above survives on disk
            obs.stop()  # final persist + harvest
            runs = observer.list_runs(hist_root)
            assert runs, "nothing persisted under the history root"
            run = observer.load_run(runs[0])
            role_names = set(run["roles"])
            assert {"ps-shard-0", "ps-shard-1"} <= role_names
            assert any(n.startswith("worker-") for n in role_names)
            s1 = run["roles"]["ps-shard-1"]["series"]
            assert "ps.accepted" in s1 and len(s1["ps.accepted"]) >= 2
            assert run["flight"], "no flight dumps in the persisted run"
        finally:
            for w in workers:
                if w.poll() is None:
                    w.kill()
            for w in workers:
                try:
                    w.wait(timeout=20.0)
                except subprocess.TimeoutExpired:
                    pass
            if obs is not None:
                obs.stop()
            if rep is not None:
                rep.stop()
            if rep_srv is not None:
                rep_srv.stop()
            group.stop()
