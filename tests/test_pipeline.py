"""Pipelined update loop (ISSUE 5): prefetched pulls, decoupled pushes,
lock-free PULL serving.

The correctness spine:

- depth=0 IS the serial loop: same accepted/dropped/staleness trajectory
  under a fixed seed AND byte-identical wire (per-op frame-byte totals),
  with the pipelined code path provably never entered;
- seeded chaos (drop_reply / cut_mid_frame) on the prefetch and push
  connections never yields a wrong model basis (the CRC machinery
  degrades to full pulls) and never double-applies a push (window
  replays are answered from the PS dedup window);
- the debug lock watchdog (net/lockwatch.py) proves no socket send/recv
  ever happens while the PS model lock is held -- the lock-free PULL
  claim -- on both a unit socketpair and a real pipelined run;
- a real two-process DCN run with pipelining on passes the
  full-coverage assert (every shard's samples contributed).
"""

import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.metrics import trace as trace_mod
from asyncframework_tpu.net import frame, lockwatch, reset_net_totals
from asyncframework_tpu.net import faults
from asyncframework_tpu.net.faults import (
    CUT_MID_FRAME,
    DROP_REPLY,
    FaultSchedule,
)
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.solvers import SolverConfig

pytestmark = pytest.mark.pipeline

CHILD = Path(__file__).parent / "ps_dcn_child.py"


def make_cfg(**kw):
    defaults = dict(
        num_workers=2, num_iterations=60, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=20, seed=42,
        calibration_iters=8, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    """Pipeline totals, wire-byte totals, and fault schedules are
    process-global; runs must neither inherit nor leak them."""
    ps_dcn.reset_pipeline_totals()
    reset_net_totals()
    faults.clear()
    yield
    ps_dcn.reset_pipeline_totals()
    reset_net_totals()
    faults.clear()
    set_global_conf(None)


def run_dcn(devices, cfg, conf, nw=None, n=1024, d=16, seed=11,
            deadline_s=120.0):
    """One in-process PS + worker-process run under ``conf``."""
    nw = nw if nw is not None else cfg.num_workers
    set_global_conf(conf)
    ds = ShardedDataset.generate_on_device(n, d, nw, devices=devices[:nw],
                                           seed=seed, noise=0.01)
    ps = ps_dcn.ParameterServer(cfg, d, n, device=devices[0], port=0).start()
    try:
        shards = {w: ds.shard(w) for w in range(nw)}
        counts = ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(nw)), shards, cfg, d, n,
            deadline_s=deadline_s,
        )
        done = ps.wait_done(timeout_s=10.0)
        return ps, counts, done
    finally:
        ps.stop()


# ------------------------------------------------------ depth=0 identity
class TestDepthZeroIsSerial:
    def test_depth0_never_enters_pipelined_path(self, devices8):
        """With the knob unset (default 0) the pipelined machinery must
        not even be touched: a serial run leaves ZERO pipeline counters
        (the pipelined loop cannot run without bumping them -- every
        consumed model ticks a hit or a wait)."""
        conf = AsyncConf().set("async.trace.sample", 0.0)
        cfg = make_cfg(num_workers=1, num_iterations=30)
        ps, counts, done = run_dcn(devices8, cfg, conf, nw=1)
        assert done and ps.accepted == 30
        assert ps_dcn.pipeline_totals() == {}

    def test_depth0_conf_set_matches_unset_byte_identical(self, devices8):
        """`async.pipeline.depth=0` is byte-identical on the wire and
        step-identical (accepted/dropped/staleness) to the knob being
        absent, under a fixed seed.  One worker + full pulls + no
        calibration makes the whole exchange deterministic, so per-op
        frame-byte totals must match EXACTLY."""
        results = []
        for depth_conf in (None, "0"):
            conf = (AsyncConf().set("async.pull.mode", "full")
                    .set("async.trace.sample", 0.0))
            if depth_conf is not None:
                conf.set("async.pipeline.depth", depth_conf)
            reset_net_totals()
            cfg = make_cfg(num_workers=1, num_iterations=40,
                           calibration_iters=10**9)
            ps, counts, done = run_dcn(devices8, cfg, conf, nw=1)
            assert done, "run did not finish"
            results.append({
                "accepted": ps.accepted,
                "dropped": ps.dropped,
                "max_staleness": ps.max_staleness,
                "clock": ps._clock,
                "pull_replies": dict(ps.pull_replies),
                "bytes": frame.bytes_totals(),
            })
        unset, zero = results
        assert unset["accepted"] == zero["accepted"] == 40
        assert unset["dropped"] == zero["dropped"]
        assert unset["max_staleness"] == zero["max_staleness"]
        assert unset["clock"] == zero["clock"]
        assert unset["pull_replies"] == zero["pull_replies"]
        # byte-identity: every op's sent/recv frame-byte totals agree
        assert unset["bytes"] == zero["bytes"], (unset["bytes"],
                                                 zero["bytes"])


# ---------------------------------------------------------- pipelined run
class TestPipelinedRun:
    def test_run_completes_with_full_coverage_and_counters(self, devices8):
        """Pipelined loop end to end: run completes, every shard
        contributed accepted gradients, the prefetch/window counters
        engaged, and the `pipeline` trace stage shows up in the
        aggregator (spans piggybacked to the PS)."""
        trace_mod.reset_aggregator()
        conf = (AsyncConf().set("async.pull.mode", "delta")
                .set("async.pipeline.depth", 2)
                .set("async.trace.sample", 0.25))
        cfg = make_cfg(num_workers=4, num_iterations=200,
                       bucket_ratio=0.5)
        ps, counts, done = run_dcn(devices8, cfg, conf, nw=4)
        assert done, "pipelined run did not finish"
        assert ps.accepted == 200
        for w in range(4):
            assert ps.accepted_by_wid.get(w, 0) > 0, ps.accepted_by_wid
        pl = ps_dcn.pipeline_totals()
        assert pl.get("pushes_async", 0) >= 200
        assert 1 <= pl.get("inflight_max", 0) <= 2
        assert (pl.get("prefetch_hits", 0)
                + pl.get("prefetch_waits", 0)) >= 200
        snap = trace_mod.aggregator().snapshot()
        assert trace_mod.PIPELINE in snap["stages_ms"], snap["stages_ms"]

    def test_asaga_ignores_pipeline_depth(self, devices8):
        """ASAGA's PS-side sampling holds one pending (idx, alpha) slot
        per wid -- the pipelined loop must never run for it, whatever
        the conf says."""
        conf = (AsyncConf().set("async.pipeline.depth", 4)
                .set("async.trace.sample", 0.0))
        set_global_conf(conf)
        n, d, nw = 512, 12, 2
        cfg = make_cfg(num_workers=nw, num_iterations=40, gamma=0.5)
        ds = ShardedDataset.generate_on_device(n, d, nw,
                                               devices=devices8[:nw],
                                               seed=3, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0], port=0,
                                    algo="asaga").start()
        try:
            shards = {w: ds.shard(w) for w in range(nw)}
            ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, list(range(nw)), shards, cfg, d, n,
                deadline_s=120.0, algo="asaga",
            )
            assert ps.wait_done(timeout_s=10.0)
            assert ps.accepted == 40
            # the serial ASAGA path leaves no pipeline counters behind
            assert ps_dcn.pipeline_totals() == {}
        finally:
            ps.stop()

    def test_taw_rejections_trigger_stale_prefetch_discards(self, devices8):
        """taw=0 makes every in-flight-stale push bounce; each rejection
        must make the worker discard its prefetched model and re-pull
        fresh (the pipelined loop's staleness feedback)."""
        conf = (AsyncConf().set("async.pull.mode", "delta")
                .set("async.pipeline.depth", 2)
                .set("async.trace.sample", 0.0))
        cfg = make_cfg(num_workers=2, num_iterations=30, taw=0)
        ps, counts, done = run_dcn(devices8, cfg, conf, nw=2)
        assert done, "taw=0 pipelined run did not finish"
        assert ps.accepted == 30
        pl = ps_dcn.pipeline_totals()
        if ps.dropped >= 2:
            assert pl.get("stale_discards", 0) >= 1, (ps.dropped, pl)


# ------------------------------------------------------------- chaos
class TestPipelineChaos:
    def test_faults_on_both_connections_never_wrong_never_double(
            self, devices8):
        """Seeded drop_reply/cut_mid_frame on the prefetch (PULL) and
        push (PUSH) connections: the run still completes exactly, the
        clock never exceeds the gradients actually computed (no push
        applied twice -- window replays hit the dedup cache), and every
        scheduled fault fired."""
        conf = (AsyncConf().set("async.pull.mode", "delta")
                .set("async.pipeline.depth", 2)
                .set("async.trace.sample", 0.0))
        set_global_conf(conf)
        n, d, nw = 1024, 16, 2
        cfg = make_cfg(num_workers=nw, num_iterations=80)
        ds = ShardedDataset.generate_on_device(n, d, nw,
                                               devices=devices8[:nw],
                                               seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        ep = f"127.0.0.1:{ps.port}"
        sched = (FaultSchedule(seed=13)
                 .add(ep, "PULL", 3, DROP_REPLY)
                 .add(ep, "PULL", 9, CUT_MID_FRAME)
                 .add(ep, "PULL", 15, DROP_REPLY)
                 .add(ep, "PUSH", 4, DROP_REPLY)
                 .add(ep, "PUSH", 11, CUT_MID_FRAME)
                 .add(ep, "PUSH", 17, DROP_REPLY))
        try:
            with faults.injected(sched) as inj:
                shards = {w: ds.shard(w) for w in range(nw)}
                counts = ps_dcn.run_worker_process(
                    "127.0.0.1", ps.port, list(range(nw)), shards, cfg,
                    d, n, deadline_s=120.0,
                )
                done = ps.wait_done(timeout_s=10.0)
                assert done, "chaos pipelined run did not finish"
                assert ps.accepted == 80
                # exactly-once: every merged push maps to one computed
                # gradient; a double-applied window replay would break
                # clock <= computed
                assert ps._clock <= sum(counts.values()), (
                    ps._clock, counts,
                )
                # the drop_reply-on-PUSH faults force window replays of
                # already-applied pushes: the dedup cache must answer
                assert ps.dedup_hits >= 1
                assert inj.remaining() == [], "all faults must fire"
        finally:
            ps.stop()


# ------------------------------------------------------- accept-loop reap
class TestAcceptLoopReap:
    def test_finished_handler_threads_are_reaped(self, devices8):
        """A long-running PS must not accumulate one Thread object per
        connection ever accepted: finished handlers are pruned on
        accept and on stop()."""
        cfg = make_cfg(num_workers=1, num_iterations=10**6)
        ps = ps_dcn.ParameterServer(cfg, 8, 64,
                                    device=devices8[0], port=0).start()
        try:
            for _ in range(12):
                cl = ps_dcn.PSClient("127.0.0.1", ps.port)
                cl.bye()
                # wait for the handler to exit before the next connect so
                # the reap-on-append has something to prune
                deadline = time.monotonic() + 5
                while (sum(t.is_alive() for t in ps._threads) > 0
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
            assert len(ps._threads) <= 3, (
                f"{len(ps._threads)} handler threads retained after 12 "
                f"sequential connections"
            )
        finally:
            ps.stop()
        # reap-on-stop dropped whatever had finished by then too
        assert len(ps._threads) <= 3


# ------------------------------------------------------------- lockwatch
class TestLockWatchdog:
    def test_socket_io_under_watched_lock_raises(self):
        """The watchdog's core contract at the frame choke point."""
        lockwatch.reset_totals()
        lockwatch.enable(True)
        try:
            a, b = socket.socketpair()
            wl = lockwatch.WatchedLock("test.model")
            with wl:
                with pytest.raises(AssertionError, match="test.model"):
                    frame.send_msg(a, {"op": "PING"})
            # outside the hold the same send goes through
            frame.send_msg(a, {"op": "PING"})
            hdr, _ = frame.recv_msg(b)
            assert hdr["op"] == "PING"
            a.close()
            b.close()
            t = lockwatch.totals()
            assert t["violations"] == 1
            assert t["holds"] >= 1
            assert t["max_hold_ms"] >= 0.0
        finally:
            lockwatch.enable(False)
            lockwatch.reset_totals()

    def test_pipelined_run_is_clean_under_watchdog(self, devices8):
        """The lock-free PULL claim, checked live: a pipelined run with
        the watchdog armed (watched PS model lock) completes with ZERO
        violations and real hold-time stats."""
        lockwatch.reset_totals()
        lockwatch.enable(True)
        try:
            conf = (AsyncConf().set("async.pull.mode", "delta")
                    .set("async.pipeline.depth", 2)
                    .set("async.trace.sample", 0.0))
            cfg = make_cfg(num_workers=2, num_iterations=60)
            ps, counts, done = run_dcn(devices8, cfg, conf, nw=2)
            assert done and ps.accepted == 60
            assert isinstance(ps._lock, lockwatch.WatchedLock)
            t = lockwatch.totals()
            assert t["violations"] == 0, t
            assert t["holds"] > 0
        finally:
            lockwatch.enable(False)
            lockwatch.reset_totals()

    def test_live_ui_snapshot_carries_pipeline_and_lockwatch(self):
        from asyncframework_tpu.metrics.live import LiveStateListener

        snap = LiveStateListener(2).snapshot()
        assert "pipeline" in snap
        assert "lockwatch" in snap
        assert set(snap["lockwatch"]) >= {"enabled", "holds",
                                          "violations", "max_hold_ms"}


# ----------------------------------------------------- two-process run
class TestTwoProcessPipelined:
    def test_real_worker_process_pipelined_full_coverage(self, devices8):
        """THE acceptance scenario: a real OS worker process runs the
        pipelined loop (depth 2, delta pulls) against an in-process PS;
        the run completes with EVERY shard's samples contributing
        accepted gradients, and the worker's pipeline counters arrive at
        the PS via the PUSH/BYE piggyback."""
        ps_dcn.reset_pipeline_totals()
        nw, n, d = 8, 4096, 24
        cfg = SolverConfig(
            num_workers=nw, num_iterations=400, gamma=1.2,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
            printer_freq=50, seed=42, calibration_iters=20,
            run_timeout_s=120.0,
        )
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update(
            PS_ROLE="worker", PS_PORT=str(ps.port), PS_WORKER_ID="0",
            PS_NUM_WORKER_PROCS="1", PS_EVAL="0", PS_NUM_ITER="400",
            ASYNCTPU_ASYNC_PIPELINE_DEPTH="2",
            ASYNCTPU_ASYNC_PULL_MODE="delta",
            ASYNCTPU_ASYNC_TRACE_SAMPLE="0.25",
        )
        try:
            worker = subprocess.run(
                [sys.executable, str(CHILD)], env=env,
                capture_output=True, text=True, timeout=180,
            )
            assert worker.returncode == 0, worker.stderr[-2000:]
            res = ps.wait_done(timeout_s=30.0)
            assert res, str(res)
        finally:
            ps.stop()
        assert ps.accepted == 400
        # full data coverage: every shard contributed accepted gradients
        for w in range(nw):
            assert ps.accepted_by_wid.get(w, 0) > 0, ps.accepted_by_wid
        # the pipelined loop really ran in the child, and its counters
        # crossed the process boundary on the piggyback
        pl = ps_dcn.pipeline_totals()
        assert pl.get("pushes_async", 0) >= 400, pl
        assert pl.get("inflight_max", 0) >= 1, pl


# --------------------------------------------------------- bench probe
class TestBenchProbeCache:
    @staticmethod
    def _hanging_popen(calls):
        import bench

        class FakeProc:
            returncode = None

            def communicate(self, timeout=None):
                raise subprocess.TimeoutExpired(cmd="probe",
                                                timeout=timeout or 1)

            def kill(self):
                pass

        def fake_popen(*a, **kw):
            calls["n"] += 1
            return FakeProc()

        return bench, fake_popen

    def test_probe_failure_cached_success_not(self, monkeypatch):
        calls = {"n": 0}
        bench, fake_popen = self._hanging_popen(calls)
        monkeypatch.setattr(bench.subprocess, "Popen", fake_popen)
        bench._PROBE_FAILURES.clear()
        try:
            alive, note = bench.probe_backend({})
            assert not alive
            assert calls["n"] == bench.PROBE_ATTEMPTS
            # second probe for the same platform: answered from cache,
            # zero new subprocess spend
            alive2, note2 = bench.probe_backend({})
            assert not alive2 and note2 == note
            assert calls["n"] == bench.PROBE_ATTEMPTS
            # a DIFFERENT platform still probes
            bench.probe_backend({"BENCH_PLATFORM": "cpu"})
            assert calls["n"] == 2 * bench.PROBE_ATTEMPTS
        finally:
            bench._PROBE_FAILURES.clear()

    def test_probe_budget_hard_bound(self, monkeypatch):
        """BENCH_PROBE_BUDGET_S caps the WHOLE probe: a hung attempt
        consumes wall clock, and once the budget is spent no further
        attempt is launched -- a dead TPU tunnel can never wedge the
        probe itself (ROADMAP item 2 leftover).  Driven with a fake
        clock so attempt 1 genuinely RUNS and eats the budget."""
        import types

        calls = {"n": 0}
        bench, _unused = self._hanging_popen(calls)
        clock = {"t": 0.0}

        class FakeProc:
            returncode = None

            def communicate(self, timeout=None):
                # a hung child: the wait consumes its whole timeout
                clock["t"] += float(timeout or 1.0)
                raise subprocess.TimeoutExpired(cmd="probe",
                                                timeout=timeout or 1)

            def kill(self):
                pass

        def fake_popen(*a, **kw):
            calls["n"] += 1
            return FakeProc()

        monkeypatch.setattr(bench.subprocess, "Popen", fake_popen)
        monkeypatch.setattr(
            bench, "time",
            types.SimpleNamespace(monotonic=lambda: clock["t"]))
        monkeypatch.setattr(bench, "PROBE_BUDGET_S", 60.0)
        monkeypatch.setattr(bench, "_reap_detached", lambda p: None)
        bench._PROBE_FAILURES.clear()
        try:
            alive, note = bench.probe_backend({"BENCH_PLATFORM": "x"})
            assert not alive and "budget" in note
            # attempt 1 RAN with its timeout capped to the remaining
            # budget (min(75, 60) = 60), consumed it all, and attempt 2
            # was never launched
            assert calls["n"] == 1
            assert clock["t"] <= 60.0 + 1e-6
        finally:
            bench._PROBE_FAILURES.clear()
