"""Checkpoint/resume tests.

The reference has no training-loop checkpointing (SURVEY.md section 5); this
is a first-class feature of the TPU build, so it gets its own layer of tests:
serialization round-trips, manager atomicity/GC, and true solver resume
(ASGD and ASAGA continue from a saved step with model, history table, clock,
and PRNG chains restored).
"""

import numpy as np
import pytest

from asyncframework_tpu.checkpoint import (
    CheckpointManager,
    load_checkpoint,
    save_checkpoint,
)
from asyncframework_tpu.data import make_regression
from asyncframework_tpu.solvers import ASAGA, ASGD, SolverConfig


class TestRoundTrip:
    def test_nested_state_round_trips(self, tmp_path):
        state = {
            "w": np.arange(8, dtype=np.float32),
            "k": 17,
            "clock": 42,
            "gamma": 0.25,
            "name": "asgd",
            "flag": True,
            "nothing": None,
            "worker_keys": {0: np.array([1, 2], np.uint32),
                            3: np.array([5, 6], np.uint32)},
            "pair": (1, 2.5),
            "lst": [np.ones(3, np.float32), "x"],
        }
        save_checkpoint(tmp_path / "ck", state)
        out = load_checkpoint(tmp_path / "ck")
        np.testing.assert_array_equal(out["w"], state["w"])
        assert out["k"] == 17 and out["clock"] == 42
        assert out["gamma"] == 0.25 and out["name"] == "asgd"
        assert out["flag"] is True and out["nothing"] is None
        # int dict keys survive the round trip as ints
        assert set(out["worker_keys"]) == {0, 3}
        np.testing.assert_array_equal(out["worker_keys"][3],
                                      state["worker_keys"][3])
        assert out["pair"] == (1, 2.5)
        np.testing.assert_array_equal(out["lst"][0], state["lst"][0])

    def test_jax_arrays_fetched_to_host(self, tmp_path):
        import jax.numpy as jnp

        save_checkpoint(tmp_path / "ck", {"w": jnp.arange(4.0)})
        out = load_checkpoint(tmp_path / "ck")
        assert isinstance(out["w"], np.ndarray)
        np.testing.assert_allclose(out["w"], [0, 1, 2, 3])

    def test_separator_in_key_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            save_checkpoint(tmp_path / "ck", {"a/b": 1})


class TestManager:
    def test_save_restore_latest_and_gc(self, tmp_path):
        mgr = CheckpointManager(tmp_path, max_to_keep=2)
        assert mgr.latest_step() is None
        assert mgr.restore_latest_or_none() is None
        for step in (10, 20, 30):
            mgr.save(step, {"w": np.full(4, step, np.float32), "k": step})
        assert mgr.all_steps() == [20, 30]  # 10 garbage-collected
        out = mgr.restore()
        assert out["k"] == 30
        out20 = mgr.restore(step=20)
        assert out20["k"] == 20
        with pytest.raises(FileNotFoundError):
            mgr.restore(step=10)

    def test_same_step_overwrite(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(5, {"k": 5, "v": 1})
        mgr.save(5, {"k": 5, "v": 2})
        assert mgr.restore(step=5)["v"] == 2
        assert mgr.all_steps() == [5]

    def test_no_partial_checkpoint_visible(self, tmp_path):
        """Only ckpt-* dirs count; stale temp dirs are not restorable state."""
        mgr = CheckpointManager(tmp_path)
        (tmp_path / ".tmp-99-99999999.npz").touch()  # pid guaranteed dead
        assert mgr.latest_step() is None
        mgr.save(1, {"k": 1})
        # a crashed foreign writer's orphan temp file was swept by gc
        assert not (tmp_path / ".tmp-99-99999999.npz").exists()
        assert mgr.all_steps() == [1]

    def test_live_writer_tmp_file_not_swept(self, tmp_path):
        """A concurrent *live* process's in-progress save must survive gc."""
        import os

        mgr = CheckpointManager(tmp_path)
        live = tmp_path / f".tmp-7-{os.getppid()}.npz"
        live.touch()
        mgr.save(1, {"k": 1})
        assert live.exists()


def resume_cfg(tmp_path, iters, **kw):
    defaults = dict(
        num_workers=8,
        num_iterations=iters,
        gamma=1.0,
        batch_rate=0.3,
        bucket_ratio=0.5,
        printer_freq=50,
        seed=42,
        calibration_iters=10,
        run_timeout_s=120.0,
        checkpoint_dir=str(tmp_path / "ckpts"),
        checkpoint_freq=25,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


class TestSolverResume:
    def test_asgd_resumes_from_saved_step(self, devices8, tmp_path):
        X, y, _ = make_regression(2048, 32, seed=3)
        res1 = ASGD(X, y, resume_cfg(tmp_path, 100), devices=devices8).run()
        assert res1.accepted == 100
        mgr = CheckpointManager(tmp_path / "ckpts")
        assert mgr.latest_step() == 100
        ck = mgr.restore()
        np.testing.assert_array_equal(ck["w"], res1.final_w)
        assert set(ck["worker_keys"]) == set(range(8))

        # second run continues 100 -> 200: only 100 new accepted updates
        res2 = ASGD(X, y, resume_cfg(tmp_path, 200), devices=devices8).run()
        assert res2.accepted == 100
        assert CheckpointManager(tmp_path / "ckpts").latest_step() == 200
        # resumed trajectory starts exactly where run 1 ended (same model,
        # same deterministic evaluation) and stays better than a cold start
        assert res2.trajectory[0][1] == pytest.approx(
            res1.trajectory[-1][1], rel=1e-4
        )
        assert res2.trajectory[-1][1] < res1.trajectory[0][1]

    def test_incompatible_resume_rejected(self, devices8, tmp_path):
        """Resuming with a different worker count / dataset / solver fails
        fast instead of crashing deep in the loop or training wrong state."""
        X, y, _ = make_regression(1024, 16, seed=4)
        ASGD(X, y, resume_cfg(tmp_path, 30), devices=devices8).run()
        with pytest.raises(ValueError, match="num_workers"):
            ASGD(X, y, resume_cfg(tmp_path, 60, num_workers=4),
                 devices=devices8).run()
        X2, y2, _ = make_regression(512, 16, seed=4)
        with pytest.raises(ValueError, match="n="):
            ASGD(X2, y2, resume_cfg(tmp_path, 60), devices=devices8).run()
        with pytest.raises(ValueError, match="solver"):
            ASAGA(X, y, resume_cfg(tmp_path, 60, gamma=0.5),
                  devices=devices8).run()

    @pytest.mark.slow
    def test_asgd_resume_noop_when_complete(self, devices8, tmp_path):
        X, y, _ = make_regression(1024, 16, seed=4)
        ASGD(X, y, resume_cfg(tmp_path, 60), devices=devices8).run()
        res = ASGD(X, y, resume_cfg(tmp_path, 60), devices=devices8).run()
        assert res.accepted == 0  # already at target iteration count

    def test_asaga_resumes_with_history_table(self, devices8, tmp_path):
        X, y, _ = make_regression(2048, 32, seed=6)
        cfg1 = resume_cfg(tmp_path, 80, gamma=0.5)
        res1 = ASAGA(X, y, cfg1, devices=devices8).run()
        assert res1.accepted == 80
        ck = CheckpointManager(tmp_path / "ckpts").restore()
        assert ck["k"] == 80
        # history table: one slice per worker, sized like its shard
        assert set(ck["alpha"]) == set(range(8))
        assert sum(a.size for a in ck["alpha"].values()) == 2048
        # at least one worker's slice has been written by an accepted update
        assert any(np.any(a != 0) for a in ck["alpha"].values())

        res2 = ASAGA(X, y, resume_cfg(tmp_path, 160, gamma=0.5),
                     devices=devices8).run()
        assert res2.accepted == 80
        # resumed run starts exactly at run 1's final model (async loss
        # comparisons beyond that are thread-timing noise, not correctness)
        assert res2.trajectory[0][1] == pytest.approx(
            res1.trajectory[-1][1], rel=1e-4
        )
        assert CheckpointManager(tmp_path / "ckpts").latest_step() == 160
