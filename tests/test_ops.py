"""Gradient / BLAS / sampling / collective op tests.

Parity with the reference's algorithm-level tests
(``GradientDescentSuite.scala:67-185``): exact gradients against closed form,
plus determinism of the seeded sampling protocol.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from asyncframework_tpu.ops import blas, collectives, gradients, sampling
from asyncframework_tpu.parallel import make_mesh, shard_batch


class TestBlas:
    def test_axpy_inplace(self):
        y = np.array([1.0, 2.0, 3.0])
        x = np.array([1.0, 1.0, 1.0])
        out = blas.axpy_op(2.0, x, y)
        assert out is y  # in place, like BLASUtil.axpyOp
        np.testing.assert_allclose(y, [3.0, 4.0, 5.0])

    def test_axpy_unit_scale(self):
        y = np.ones(4)
        out = blas.axpy_op(1.0, np.arange(4.0), y)
        np.testing.assert_allclose(out, [1, 2, 3, 4])

    def test_dot_scal(self):
        x = np.array([1.0, 2.0])
        assert blas.dot_op(x, x) == pytest.approx(5.0)
        out = blas.scal_op(0.5, x)
        assert out is x
        np.testing.assert_allclose(x, [0.5, 1.0])

    def test_readonly_buffers_fall_back_out_of_place(self):
        # np.asarray(jax_array) exposes device buffers read-only; the updater
        # hot loop must not crash on them.
        g = np.asarray(jnp.arange(4.0))
        assert not g.flags.writeable
        out = blas.scal_op(2.0, g)
        np.testing.assert_allclose(out, [0, 2, 4, 6])
        w = np.asarray(jnp.ones(4))
        out2 = blas.axpy_op(0.5, g, w)
        np.testing.assert_allclose(out2, [1, 1.5, 2, 2.5])

    def test_jax_arrays_supported(self):
        y = jnp.ones(3)
        out = blas.axpy_op(2.0, jnp.arange(3.0), y)
        np.testing.assert_allclose(np.asarray(out), [1, 3, 5])


class TestGradients:
    def test_least_squares_exact(self, tiny_problem):
        X, y, _ = tiny_problem
        w = np.full(X.shape[1], 0.1, np.float32)
        mask = np.ones(X.shape[0], np.float32)
        g = gradients.least_squares_grad_sum(X, y, w, mask)
        expected = X.T @ (X @ w - y)
        np.testing.assert_allclose(np.asarray(g), expected, rtol=2e-4)

    def test_least_squares_masked_equals_subset(self, tiny_problem):
        X, y, _ = tiny_problem
        w = np.full(X.shape[1], -0.3, np.float32)
        mask = np.zeros(X.shape[0], np.float32)
        mask[::3] = 1.0
        g = gradients.least_squares_grad_sum(X, y, w, mask)
        sub = np.flatnonzero(mask)
        expected = X[sub].T @ (X[sub] @ w - y[sub])
        np.testing.assert_allclose(np.asarray(g), expected, rtol=2e-4, atol=1e-3)

    def test_per_sample_gradfun_parity(self):
        # gradfun(p, w) = (x.w - y) * x summed over batch == matmul form
        rs = np.random.default_rng(1)
        X = rs.normal(size=(10, 4)).astype(np.float32)
        y = rs.normal(size=(10,)).astype(np.float32)
        w = rs.normal(size=(4,)).astype(np.float32)
        per_sample = sum((X[i] @ w - y[i]) * X[i] for i in range(10))
        g = gradients.least_squares_grad_sum(X, y, w, np.ones(10, np.float32))
        np.testing.assert_allclose(np.asarray(g), per_sample, rtol=1e-4)

    def test_logistic_grad_matches_autodiff(self, tiny_problem):
        X, y, _ = tiny_problem
        yb = (y > 0).astype(np.float32)
        w = np.full(X.shape[1], 0.05, np.float32)
        mask = np.ones(X.shape[0], np.float32)
        g = gradients.logistic_grad_sum(X, yb, w, mask)
        auto = jax.grad(lambda w_: gradients.logistic_loss(X, yb, w_))(jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(g), np.asarray(auto), rtol=1e-3, atol=1e-3)

    def test_loss_decreases_under_gd(self, tiny_problem):
        # "loss is decreasing" -- GradientDescentSuite parity
        X, y, _ = tiny_problem
        n = X.shape[0]
        w = np.zeros(X.shape[1], np.float32)
        mask = np.ones(n, np.float32)
        losses = []
        for _ in range(20):
            losses.append(float(gradients.least_squares_loss(X, y, w)) / n)
            g = np.asarray(gradients.least_squares_grad_sum(X, y, w, mask))
            w -= 0.01 / n * g
        assert all(b < a for a, b in zip(losses, losses[1:]))

    def test_saga_shard_step(self):
        rs = np.random.default_rng(2)
        X = rs.normal(size=(12, 5)).astype(np.float32)
        y = rs.normal(size=(12,)).astype(np.float32)
        w = rs.normal(size=(5,)).astype(np.float32)
        alpha = rs.normal(size=(12,)).astype(np.float32)
        mask = (rs.random(12) < 0.5).astype(np.float32)
        g, diff = gradients.saga_shard_step(X, y, w, alpha, mask)
        np.testing.assert_allclose(np.asarray(diff), X @ w - y, rtol=1e-4)
        expected = X.T @ (mask * ((X @ w - y) - alpha))
        np.testing.assert_allclose(np.asarray(g), expected, rtol=1e-4, atol=1e-4)
        committed = gradients.saga_commit_history(alpha, diff, mask)
        np.testing.assert_allclose(
            np.asarray(committed), np.where(mask > 0, X @ w - y, alpha), rtol=1e-4
        )


class TestSampling:
    def test_mask_deterministic(self):
        m1 = sampling.host_mask(42, 7, 3, 1000, 0.1)
        m2 = sampling.host_mask(42, 7, 3, 1000, 0.1)
        np.testing.assert_array_equal(m1, m2)

    def test_mask_varies_by_round_and_worker(self):
        base = sampling.host_mask(42, 7, 3, 1000, 0.1)
        assert not np.array_equal(base, sampling.host_mask(42, 8, 3, 1000, 0.1))
        assert not np.array_equal(base, sampling.host_mask(42, 7, 4, 1000, 0.1))

    def test_mask_rate(self):
        m = sampling.host_mask(0, 0, 0, 20000, 0.1)
        assert abs(m.mean() - 0.1) < 0.01

    def test_driver_worker_agreement(self):
        """The driver can reproduce a worker's draw exactly (ASAGA cTime parity)."""
        key = sampling.worker_key(42, 11, 5)
        on_worker = np.asarray(sampling.bernoulli_mask(key, 256, 0.3))
        on_driver = sampling.host_mask(42, 11, 5, 256, 0.3)
        np.testing.assert_array_equal(on_worker, on_driver)


class TestCollectives:
    def test_tree_combine_matches_fold(self):
        xs = [np.full(3, float(i)) for i in range(9)]
        out = collectives.tree_combine(xs, lambda a, b: a + b)
        np.testing.assert_allclose(out, np.full(3, sum(range(9))))

    def test_tree_combine_empty_raises(self):
        with pytest.raises(ValueError):
            collectives.tree_combine([], lambda a, b: a + b)

    def test_data_parallel_grad_matches_single_device(self, devices8, tiny_problem):
        X, y, _ = tiny_problem
        mesh = make_mesh(8, devices=devices8)
        w = np.full(X.shape[1], 0.2, np.float32)
        mask = np.ones(X.shape[0], np.float32)
        fn = collectives.data_parallel_grad_fn(
            gradients.least_squares_grad_sum, mesh
        )
        Xs, ys, ms = shard_batch(mesh, X, y, mask)
        g = fn(Xs, ys, jnp.asarray(w), ms)
        expected = X.T @ (X @ w - y)
        np.testing.assert_allclose(np.asarray(g), expected, rtol=2e-4, atol=1e-2)


class TestBatchedApply:
    def test_batch_apply_matches_sequential(self):
        from asyncframework_tpu.ops import steps

        rs = np.random.default_rng(0)
        d, m = 32, 6
        gamma, b, n, nw = 0.7, 0.1, 10_000, 8
        w0 = rs.normal(size=d).astype(np.float32)
        G = rs.normal(size=(m, d)).astype(np.float32)

        apply_one = steps.make_asgd_apply(gamma, b, n, nw)
        w_seq = jnp.asarray(w0)
        k = jnp.float32(5.0)
        for i in range(m):
            w_seq, k = apply_one(w_seq, jnp.asarray(G[i]), k)

        apply_many = steps.make_asgd_apply_batch(gamma, b, n, nw, m)
        w_bat, k_bat = apply_many(
            jnp.asarray(w0), jnp.asarray(G),
            jnp.ones(m, jnp.float32), jnp.float32(5.0),
        )
        np.testing.assert_allclose(np.asarray(w_bat), np.asarray(w_seq),
                                   rtol=1e-5, atol=1e-6)
        assert float(k_bat) == float(k)

    def test_batch_apply_mask_skips_slots(self):
        from asyncframework_tpu.ops import steps

        rs = np.random.default_rng(1)
        d = 16
        w0 = rs.normal(size=d).astype(np.float32)
        G = rs.normal(size=(4, d)).astype(np.float32)
        mask = jnp.asarray([1.0, 0.0, 1.0, 0.0])

        apply_many = steps.make_asgd_apply_batch(0.5, 0.1, 1000, 4, 4)
        w_bat, k_bat = apply_many(
            jnp.asarray(w0), jnp.asarray(G), mask, jnp.float32(0.0)
        )
        apply_one = steps.make_asgd_apply(0.5, 0.1, 1000, 4)
        w_seq, k = jnp.asarray(w0), jnp.float32(0.0)
        for i in (0, 2):
            w_seq, k = apply_one(w_seq, jnp.asarray(G[i]), k)
        np.testing.assert_allclose(np.asarray(w_bat), np.asarray(w_seq),
                                   rtol=1e-5, atol=1e-6)
        assert float(k_bat) == 2.0
