"""Test harness: force an 8-device virtual CPU platform before jax imports.

Parity with the reference's test strategy (SURVEY.md section 4): the analog of
Spark's single-JVM ``local-cluster[n,cores,mem]`` is a single-process JAX
runtime with ``--xla_force_host_platform_device_count=8`` -- real shardings,
real (emulated) collectives, no real pod.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize imports jax at interpreter start (to register the
# axon TPU plugin), so JAX_PLATFORMS from the env is already latched -- force
# the CPU platform through the config API as well (backends are not yet
# initialized when conftest runs, so this takes effect).
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session", autouse=True)
def _lockorder_gate():
    """Session-wide lock-order deadlock gate (net/lockwatch.py): any
    suite that armed the watchdog (chaos/pipeline fixtures, chaos_sweep
    seeds via ASYNCTPU_ASYNC_DEBUG_LOCKWATCH) and produced an
    acquisition-order cycle among watched locks fails the session at
    teardown, whichever test happened to interleave it.  Suites that
    deliberately drive cycles (tests/test_analysis.py, the sweep's
    lockorder_sanity) clear the sticky history in their own teardown;
    everyone else's reset_totals() FOLDS cycles into that history
    instead of erasing them, so a cycle from any armed suite reaches
    this gate even if a later suite reset the live graph."""
    yield
    from asyncframework_tpu.net import lockwatch

    lockwatch.assert_no_cycles(include_history=True)


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def tiny_problem():
    """Small well-conditioned least-squares problem shared by solver tests."""
    rs = np.random.default_rng(0)
    n, d = 512, 16
    X = rs.normal(size=(n, d)).astype(np.float32)
    w_true = rs.normal(size=(d,)).astype(np.float32)
    y = (X @ w_true + 0.01 * rs.normal(size=(n,))).astype(np.float32)
    return X, y, w_true
