"""ML library tests.

Modeled on the reference's ``GradientDescentSuite`` (loss decreasing, exact
first-iteration gradient with regularization, convergence-tol termination),
``LBFGSSuite`` (matches/beats GD on the same objective), and KMeans suites.
Runs on the 8-device virtual CPU mesh from conftest.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from asyncframework_tpu.ml import (
    GradientDescent,
    HingeGradient,
    KMeans,
    LBFGS,
    L1Updater,
    LeastSquaresGradient,
    LinearRegression,
    LinearSVM,
    LogisticGradient,
    LogisticRegression,
    SimpleUpdater,
    SquaredL2Updater,
)
from asyncframework_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def mesh(request):
    return make_mesh(8)


@pytest.fixture(scope="module")
def regression_problem():
    rs = np.random.default_rng(3)
    n, d = 1024, 12
    X = rs.normal(size=(n, d)).astype(np.float32)
    w_true = rs.normal(size=(d,)).astype(np.float32)
    y = (X @ w_true + 0.05 * rs.normal(size=(n,))).astype(np.float32)
    return X, y, w_true


@pytest.fixture(scope="module")
def classification_problem():
    rs = np.random.default_rng(4)
    n, d = 1024, 8
    X = rs.normal(size=(n, d)).astype(np.float32)
    # scale up the planted weights so the Bayes classifier is well above
    # the asserted accuracy (labels are still noisy Bernoulli draws)
    w_true = (3.0 * rs.normal(size=(d,))).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rs.random(n) < p).astype(np.float32)
    return X, y, w_true


# ---------------------------------------------------------------- gradients
def test_gradients_match_autodiff():
    """Analytic batched gradients == jax.grad of the summed loss."""
    import jax

    rs = np.random.default_rng(0)
    X = jnp.asarray(rs.normal(size=(32, 5)).astype(np.float32))
    w = jnp.asarray(rs.normal(size=(5,)).astype(np.float32))
    mask = jnp.asarray((rs.random(32) < 0.7).astype(np.float32))

    for grad_obj, y in [
        (LeastSquaresGradient(),
         jnp.asarray(rs.normal(size=(32,)).astype(np.float32))),
        (LogisticGradient(),
         jnp.asarray((rs.random(32) < 0.5).astype(np.float32))),
        (HingeGradient(),
         jnp.asarray((rs.random(32) < 0.5).astype(np.float32))),
    ]:
        g, loss = grad_obj.local(X, y, w, mask)
        loss_fn = lambda ww: grad_obj.local(X, y, ww, mask)[1]  # noqa: E731
        g_auto = jax.grad(loss_fn)(w)
        # hinge is nondifferentiable on the margin boundary; off-boundary
        # points (generic random data) agree exactly
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_auto),
                                   rtol=1e-4, atol=1e-4)


def test_exact_first_iteration_gradient_with_l2(regression_problem, mesh):
    """GradientDescentSuite parity: one full-batch iteration from w0 equals
    the hand-computed update w0 - lr*(avg_grad) with L2 shrinkage."""
    X, y, _ = regression_problem
    w0 = np.ones(X.shape[1], np.float32)
    reg = 0.1
    gd = GradientDescent(
        gradient=LeastSquaresGradient(),
        updater=SquaredL2Updater(),
        step_size=1.0,
        num_iterations=1,
        reg_param=reg,
        mini_batch_fraction=1.0,
        seed=0,
    )
    w1, losses = gd.optimize(X, y, w0=w0, mesh=mesh)
    r = X @ w0 - y
    avg_grad = X.T @ r / X.shape[0]
    expected = w0 * (1.0 - 1.0 * reg) - avg_grad  # lr = 1/sqrt(1) = 1
    np.testing.assert_allclose(w1, expected, rtol=2e-4, atol=2e-4)
    # recorded loss is the pre-update objective; its regularization term is
    # seeded from the INITIAL weights (MLlib GradientDescent.scala:251-253)
    reg0 = 0.5 * reg * float(w0 @ w0)
    np.testing.assert_allclose(
        losses[0], 0.5 * float(r @ r) / X.shape[0] + reg0, rtol=1e-4
    )


def test_loss_decreasing_and_converges(regression_problem, mesh):
    X, y, w_true = regression_problem
    gd = GradientDescent(
        step_size=1.0, num_iterations=300, mini_batch_fraction=0.5, seed=1
    )
    w, losses = gd.optimize(X, y, mesh=mesh)
    assert losses[-1] < 0.05 * losses[0]
    # trajectory snapshots recorded (the fork's Warray delta)
    assert len(gd.get_all_weights()) >= 1
    assert np.linalg.norm(w - w_true) / np.linalg.norm(w_true) < 0.2


def test_convergence_tol_stops_early(regression_problem, mesh):
    X, y, _ = regression_problem
    gd = GradientDescent(
        step_size=1.0,
        num_iterations=500,
        mini_batch_fraction=1.0,
        convergence_tol=1e-3,
        seed=1,
    )
    _w, losses = gd.optimize(X, y, mesh=mesh)
    assert len(losses) < 500  # stopped before the cap


def test_l1_updater_sparsifies(mesh):
    rs = np.random.default_rng(5)
    n, d = 512, 16
    X = rs.normal(size=(n, d)).astype(np.float32)
    w_true = np.zeros(d, np.float32)
    w_true[:3] = [2.0, -3.0, 1.5]  # only 3 active features
    y = (X @ w_true + 0.01 * rs.normal(size=(n,))).astype(np.float32)
    gd = GradientDescent(
        updater=L1Updater(), step_size=0.5, num_iterations=300,
        reg_param=0.1, seed=2,
    )
    w, _ = gd.optimize(X, y, mesh=mesh)
    assert np.sum(np.abs(w[3:]) < 0.05) >= d - 5  # tail shrunk to ~0
    assert np.all(np.abs(w[:3]) > 0.5)


def test_weight_history_cadence_and_final(regression_problem, mesh):
    X, y, _ = regression_problem
    # stochastic batches keep iterates jittering, so distinct snapshot slots
    # must hold distinct iterates (full batch converges to a fixed point
    # before iteration 100, which would make the distinctness check vacuous)
    gd = GradientDescent(step_size=1.0, num_iterations=250,
                         mini_batch_fraction=0.3, seed=0, snapshot_every=100)
    w, _ = gd.optimize(X, y, mesh=mesh)
    hist = gd.get_all_weights()
    # iterations 100, 200, plus the final iterate (250 not a multiple)
    assert len(hist) == 3
    np.testing.assert_allclose(hist[-1][1], w, rtol=1e-6)
    ts = [t for t, _w in hist]
    assert ts == sorted(ts)
    # snapshots differ from one another (really distinct iterates)
    assert np.linalg.norm(hist[0][1] - hist[1][1]) > 0


def test_optimize_reuses_compiled_program(regression_problem, mesh):
    X, y, _ = regression_problem
    gd = GradientDescent(step_size=1.0, num_iterations=5, seed=0)
    gd.optimize(X, y, mesh=mesh)
    assert len(gd._train_cache) == 1
    gd.optimize(X, y, mesh=mesh)  # same shape -> same compiled program
    assert len(gd._train_cache) == 1


def test_zero_row_samples_skip_update(mesh):
    """MLlib parity: an iteration whose Bernoulli draw selects no rows must
    neither move the weights (L2 would decay them) nor append to the loss
    history."""
    rs = np.random.default_rng(11)
    n, d = 8, 4  # tiny dataset + tiny fraction -> many empty draws
    X = rs.normal(size=(n, d)).astype(np.float32)
    y = rs.normal(size=(n,)).astype(np.float32)
    w0 = np.ones(d, np.float32)
    gd = GradientDescent(
        updater=SquaredL2Updater(), step_size=0.0, num_iterations=200,
        reg_param=0.5, mini_batch_fraction=0.01, seed=0,
    )
    # step_size=0: any weight movement could only come from the L2 shrink
    # being applied on empty draws (w *= (1 - lr*reg) with lr=0 is identity,
    # so assert the *loss history length* reflects skipped iterations)
    w, losses = gd.optimize(X, y, w0=w0, mesh=mesh)
    assert len(losses) < 200  # empty draws appended no history entries
    np.testing.assert_allclose(w, w0, rtol=1e-6)


def test_lbfgs_history_resets_between_runs(regression_problem, mesh):
    X, y, _ = regression_problem
    lb = LBFGS(max_iterations=10)
    lb.optimize(X, y, mesh=mesh)
    n1 = len(lb.get_all_weights())
    lb.optimize(X, y, mesh=mesh)
    assert len(lb.get_all_weights()) == n1  # not doubled


# -------------------------------------------------------------------- LBFGS
def test_lbfgs_beats_sgd_on_full_batch(regression_problem, mesh):
    X, y, _ = regression_problem
    lb = LBFGS(max_iterations=50, reg_param=0.0)
    w_lb, hist = lb.optimize(X, y, mesh=mesh)
    gd = GradientDescent(step_size=1.0, num_iterations=50,
                         mini_batch_fraction=1.0, seed=0)
    w_gd, _ = gd.optimize(X, y, mesh=mesh)

    def obj(w):
        r = X @ w - y
        return 0.5 * float(r @ r) / X.shape[0]

    assert obj(w_lb) <= obj(w_gd) + 1e-6
    assert hist[-1] < hist[0]
    assert len(lb.get_all_weights()) >= 1


def test_lbfgs_logistic(classification_problem, mesh):
    X, y, w_true = classification_problem
    lb = LBFGS(gradient=LogisticGradient(), max_iterations=60,
               reg_param=1e-3)
    w, hist = lb.optimize(X, y, mesh=mesh)
    acc = np.mean(((X @ w) > 0) == (y > 0.5))
    assert acc > 0.85
    assert hist[-1] < hist[0]


# -------------------------------------------------------------------- models
def test_linear_regression_with_intercept(mesh):
    rs = np.random.default_rng(6)
    n, d = 512, 6
    X = rs.normal(size=(n, d)).astype(np.float32)
    w_true = rs.normal(size=(d,)).astype(np.float32)
    y = (X @ w_true + 2.5 + 0.01 * rs.normal(size=(n,))).astype(np.float32)
    m = LinearRegression(
        step_size=1.0, num_iterations=300, fit_intercept=True, seed=0
    ).fit(X, y, mesh=mesh)
    assert abs(m.intercept - 2.5) < 0.2
    rmse = np.sqrt(np.mean((m.predict(X) - y) ** 2))
    assert rmse < 0.2
    assert len(m.weight_history) >= 1


def test_logistic_regression_accuracy(classification_problem, mesh):
    X, y, _ = classification_problem
    m = LogisticRegression(step_size=2.0, num_iterations=200, seed=0).fit(
        X, y, mesh=mesh
    )
    assert np.mean(m.predict(X) == y) > 0.85
    p = m.predict_proba(X)
    assert np.all((p >= 0) & (p <= 1))


def test_svm_separable(mesh):
    rs = np.random.default_rng(7)
    n, d = 512, 4
    X = rs.normal(size=(n, d)).astype(np.float32)
    w_true = np.array([1.0, -1.0, 0.5, 2.0], np.float32)
    y = ((X @ w_true) > 0).astype(np.float32)
    m = LinearSVM(step_size=1.0, num_iterations=200, reg_param=0.01,
                  seed=0).fit(X, y, mesh=mesh)
    assert np.mean(m.predict(X) == y) > 0.93


# ----------------------------------------------------------------- clustering
def test_kmeans_recovers_separated_blobs(mesh):
    rs = np.random.default_rng(8)
    k, per, d = 4, 200, 8
    true_centers = rs.normal(size=(k, d)).astype(np.float32) * 10.0
    X = np.concatenate(
        [tc + rs.normal(size=(per, d)).astype(np.float32) for tc in true_centers]
    )
    km = KMeans(k=k, max_iterations=30, seed=1)
    model = km.fit(X, mesh=mesh)
    # each true center has a learned center within noise distance
    d2 = ((true_centers[:, None, :] - model.centers[None, :, :]) ** 2).sum(-1)
    assert np.all(d2.min(axis=1) < 2.0 * d)
    # predictions: same-blob points share a label
    labels = model.predict(X)
    for i in range(k):
        blob = labels[i * per : (i + 1) * per]
        assert np.mean(blob == np.bincount(blob).argmax()) > 0.95
    assert model.cost > 0


def test_kmeans_cost_decreases_with_k(mesh):
    rs = np.random.default_rng(9)
    X = rs.normal(size=(600, 5)).astype(np.float32)
    costs = [
        KMeans(k=k, max_iterations=15, seed=0).fit(X, mesh=mesh).cost
        for k in (2, 4, 8)
    ]
    assert costs[0] > costs[1] > costs[2]
