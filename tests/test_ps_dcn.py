"""Async parameter server across the process boundary (VERDICT r2 item 3).

The reference's flagship capability is async gradient flow from REMOTE
workers to the driver (CoarseGrainedSchedulerBackend.scala:239-307,
CoarseGrainedExecutorBackend.scala:92).  These tests run the TPU build's
DCN analog (parallel/ps_dcn.py): first fully in-process (protocol logic,
tau filter, cohort waves, convergence), then as REAL separate OS processes
pushing gradients over loopback TCP to a PS process -- the deployment shape
a multi-host v5e pod would use.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.solvers import SolverConfig

CHILD = Path(__file__).parent / "ps_dcn_child.py"


def make_cfg(**kw):
    defaults = dict(
        num_workers=8, num_iterations=300, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.5, printer_freq=50, seed=42,
        calibration_iters=20, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


class TestInProcess:
    def test_converges_and_bookkeeps(self, devices8):
        cfg = make_cfg()
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        shards = {w: ds.shard(w) for w in range(8)}
        counts = ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(8)), shards, cfg, d, n,
            eval_wid=0, deadline_s=120.0,
        )
        assert ps.wait_done(timeout_s=5.0)
        total = ps.collect_eval(num_worker_procs=1, timeout_s=30.0)
        ps.stop()
        assert ps.accepted == cfg.num_iterations
        assert sum(counts.values()) >= cfg.num_iterations
        # staleness is bounded by the total merge count (the logical clock
        # keeps ticking for post-done and dropped pushes)
        assert ps.max_staleness <= ps.accepted + ps.dropped
        assert total is not None
        traj = total / n
        assert traj[-1] < traj[0] * 0.05, traj

    def test_taw_zero_drops_under_overlap(self, devices8):
        cfg = make_cfg(taw=0, num_iterations=150)
        n, d = 2048, 16
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=3, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        shards = {w: ds.shard(w) for w in range(8)}
        ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(8)), shards, cfg, d, n,
            deadline_s=120.0,
        )
        done = ps.wait_done(timeout_s=5.0)
        ps.stop()
        assert done and ps.accepted == 150
        # 8 concurrent pullers against tau=0: overlap must show up as drops
        # unless no overlap ever happened (then max_staleness stayed 0)
        assert ps.dropped > 0 or ps.max_staleness == 0

    def test_cohort_wave_serves_threshold_together(self, devices8):
        """bucket_ratio waves: with threshold 4, pulls are released in
        groups -- the first 3 pullers block until the 4th arrives."""
        cfg = make_cfg(bucket_ratio=0.5, num_iterations=10)
        ps = ps_dcn.ParameterServer(cfg, 8, 800, device=devices8[0],
                                    port=0).start()
        released = []
        lock = threading.Lock()

        def puller(wid):
            cl = ps_dcn.PSClient("127.0.0.1", ps.port)
            got = cl.pull(wid)
            with lock:
                released.append((wid, time.monotonic()))
            cl.bye()
            assert got is not None

        threads = [threading.Thread(target=puller, args=(w,)) for w in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # below the 1s starvation-release fallback
        with lock:
            early = len(released)
        assert early == 0, released
        t4 = threading.Thread(target=puller, args=(3,))
        t4.start()
        for t in threads + [t4]:
            t.join(timeout=10)
        assert len(released) == 4
        ps.stop()


class TestASAGAInProcess:
    def test_asaga_converges_and_commits_history(self, devices8):
        """DCN ASAGA (VERDICT r3 item 3): PS owns the scalar-history table
        and the sampling; workers push (gradient, candidate scalars); the
        PS applies the three-term update + alpha_bar mean and commits the
        ScalarMap merge on accept."""
        cfg = make_cfg(gamma=0.35, num_iterations=300)
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0, algo="asaga").start()
        shards = {w: ds.shard(w) for w in range(8)}
        counts = ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(8)), shards, cfg, d, n,
            eval_wid=0, deadline_s=120.0, algo="asaga",
        )
        assert ps.wait_done(timeout_s=5.0)
        total = ps.collect_eval(num_worker_procs=1, timeout_s=30.0)
        ps.stop()
        assert ps.accepted == cfg.num_iterations
        assert sum(counts.values()) >= cfg.num_iterations
        # the ScalarMap merge ran: every worker's table slice has committed
        # scalars, and together the slices cover the whole dataset
        assert sorted(ps._table) == list(range(8))
        assert sum(t.shape[0] for t in ps._table.values()) == n
        assert all(np.any(t != 0.0) for t in ps._table.values())
        traj = np.asarray(total) / n
        assert traj[-1] < traj[0] * 0.05, traj

    def test_asaga_matches_single_process_trajectory_band(self, devices8):
        """The multi-process ASAGA reaches the same objective band as the
        single-process solver on the identical recipe (same dataset seed,
        gamma, taw, batch rate) -- the VERDICT's done-criterion."""
        from asyncframework_tpu.solvers import ASAGA

        cfg = make_cfg(gamma=0.35, num_iterations=250)
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        single = ASAGA(ds, None, cfg, devices=devices8).run()
        assert single.accepted == cfg.num_iterations

        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0, algo="asaga").start()
        shards = {w: ds.shard(w) for w in range(8)}
        ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(8)), shards, cfg, d, n,
            eval_wid=0, deadline_s=120.0, algo="asaga",
        )
        assert ps.wait_done(timeout_s=5.0)
        total = ps.collect_eval(num_worker_procs=1, timeout_s=30.0)
        ps.stop()
        dcn_traj = np.asarray(total) / n
        single_final = single.trajectory[-1][1]
        dcn_final = dcn_traj[-1]
        # different async interleavings, same contraction: the DCN run's
        # final objective lands within a small factor of the single-process
        # run's (both deep below the initial objective)
        assert dcn_final < dcn_traj[0] * 0.05
        assert dcn_final < max(single_final * 3.0, 1e-8), (
            dcn_final, single_final,
        )


@pytest.mark.slow
class TestSparseDCN:
    """rcv1-shaped shards over the DCN wire (VERDICT r3 item 4): sparse
    worker steps + (idx, val) pair PUSH encoding with wire bytes well under
    the dense d*4."""

    def _run(self, devices8, algo, gamma):
        from asyncframework_tpu.data.sparse import SparseShardedDataset

        n, d, nnz = 4096, 8192, 4   # d >> touched columns: sparse enc wins
        cfg = make_cfg(gamma=gamma, num_iterations=500, batch_rate=0.3)
        ds = SparseShardedDataset.generate_on_device(
            n, d, nnz, 8, devices=devices8, seed=7, noise=0.01
        )
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0, algo=algo).start()
        shards = {w: ds.shard(w) for w in range(8)}
        counts = ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(8)), shards, cfg, d, n,
            eval_wid=0, deadline_s=180.0, algo=algo,
        )
        assert ps.wait_done(timeout_s=5.0)
        total = ps.collect_eval(num_worker_procs=1, timeout_s=30.0)
        ps.stop()
        assert ps.accepted == cfg.num_iterations
        assert sum(counts.values()) >= cfg.num_iterations
        pushes = ps.accepted + ps.dropped
        dense_bytes = pushes * d * 4
        assert ps.push_bytes < dense_bytes / 4, (
            f"sparse wire did not shrink: {ps.push_bytes} vs dense "
            f"{dense_bytes}"
        )
        traj = np.asarray(total) / n
        assert traj[-1] < traj[0] * 0.05, traj

    # step sizes: the per-sample coefficient gamma/parRecs must stay well
    # under 2/||x||^2 = 2 (gamma = 0.5*parRecs here) or async overlap tips
    # individual sample directions unstable; ASAGA's constant step needs
    # ~4x more headroom than ASGD's sqrt-decayed one (measured)
    def test_sparse_asgd_converges_small_wire(self, devices8):
        self._run(devices8, "asgd", gamma=76.8)

    def test_sparse_asaga_converges_small_wire(self, devices8):
        self._run(devices8, "asaga", gamma=20.0)


class TestWorkerDeath:
    def test_run_survives_a_killed_worker_group_mid_run(self, devices8):
        """Multi-process fault tolerance: 5 of 8 workers die MID-RUN
        (sockets dropped, no goodbye), leaving 3 survivors -- fewer than
        the cohort threshold of 4 -- so completion additionally proves
        the starvation fallback keeps waves flowing."""
        import threading as th

        cfg = make_cfg(num_iterations=60, bucket_ratio=0.5,
                       printer_freq=20)
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        shards = {w: ds.shard(w) for w in range(3)}

        doomed_stop = th.Event()
        doomed_pushes = {"n": 0}

        def doomed():
            # workers 3..7 participate normally until killed mid-run
            clients = {
                wid: ps_dcn.PSClient("127.0.0.1", ps.port)
                for wid in range(3, 8)
            }
            try:
                while not doomed_stop.is_set():
                    for wid, c in clients.items():
                        got = c.pull(wid)
                        if got is None or doomed_stop.is_set():
                            return
                        ts, _w_host, _avg, _cal = got
                        c.push(wid, ts, np.zeros(d, np.float32))
                        doomed_pushes["n"] += 1
            except (ConnectionError, OSError):
                return
            finally:
                for c in clients.values():
                    try:
                        c.sock.close()  # abrupt death, no BYE
                    except OSError:
                        pass

        t_doomed = th.Thread(target=doomed, daemon=True)
        t_doomed.start()

        survivor_counts = {}

        def survivors():
            survivor_counts.update(ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, list(range(3)), shards, cfg, d, n,
                deadline_s=180.0,
            ))

        t_surv = th.Thread(target=survivors, daemon=True)
        t_surv.start()
        # let the full 8-worker run get underway, then kill the group
        deadline = time.monotonic() + 30
        while doomed_pushes["n"] < 5 and time.monotonic() < deadline:
            time.sleep(0.05)
        doomed_stop.set()
        t_doomed.join(timeout=15)
        assert doomed_pushes["n"] >= 5, "doomed group never participated"

        t_surv.join(timeout=180)
        done = ps.wait_done(timeout_s=30.0)
        ps.stop()
        assert done, "run did not finish after a worker group died mid-run"
        assert ps.accepted == cfg.num_iterations
        assert sum(survivor_counts.values()) > 0


@pytest.mark.slow
@pytest.mark.soak
@pytest.mark.parametrize("algo", ["asgd", "asaga"])
class TestPSCheckpointResume:
    def test_kill9_ps_midrun_restart_resumes_and_converges(
        self, algo, devices8, tmp_path
    ):
        """VERDICT r3 item 6, the exact done-criterion: kill -9 the PS
        process mid-run, restart it from its checkpoint, workers reconnect,
        and the run completes to target.  State proven restored: model,
        clock, accepted count, snapshots, and (ASAGA) the history table +
        PS-side RNG chains."""
        import signal
        import threading as th

        ckpt = str(tmp_path / "ps.npz")
        env_base = dict(os.environ)
        env_base.pop("JAX_PLATFORMS", None)
        env_base.pop("XLA_FLAGS", None)
        env = dict(
            env_base, PS_ROLE="ps", PS_ALGO=algo, PS_NUM_WORKER_PROCS="1",
            PS_CHECKPOINT=ckpt,
            PS_GAMMA="0.35" if algo == "asaga" else "1.2",
        )
        ps_proc = subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        restarted = None
        try:
            port = json.loads(ps_proc.stdout.readline())["port"]

            # workers live in THIS process and must survive the PS restart
            from asyncframework_tpu.data.sharded import ShardedDataset

            n, d = 4096, 24
            cfg = SolverConfig(
                num_workers=8, num_iterations=400,
                gamma=0.35 if algo == "asaga" else 1.2,
                taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
                printer_freq=50, seed=42, calibration_iters=20,
                run_timeout_s=240.0,
            )
            ds = ShardedDataset.generate_on_device(
                n, d, 8, devices=devices8, seed=11, noise=0.01
            )
            shards = {w: ds.shard(w) for w in range(8)}
            counts = {}

            def workers():
                counts.update(ps_dcn.run_worker_process(
                    "127.0.0.1", port, list(range(8)), shards, cfg, d, n,
                    eval_wid=0, deadline_s=240.0, algo=algo,
                ))

            t_w = th.Thread(target=workers, daemon=True)
            t_w.start()

            # wait for the first on-disk checkpoint (k >= printer_freq),
            # then kill the PS dead -- no goodbye, no flush
            deadline = time.monotonic() + 120
            while not os.path.exists(ckpt) and time.monotonic() < deadline:
                time.sleep(0.05)
            assert os.path.exists(ckpt), "no checkpoint ever written"
            ps_proc.send_signal(signal.SIGKILL)
            ps_proc.wait(timeout=10)

            # restart from the checkpoint on the SAME port; workers are in
            # their reconnect loop and must pick up where they left off
            env_r = dict(env, PS_BIND_PORT=str(port))
            restarted = subprocess.Popen(
                [sys.executable, str(CHILD)], env=env_r,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            assert json.loads(restarted.stdout.readline())["port"] == port
            t_w.join(timeout=240)
            assert not t_w.is_alive(), "workers never finished after restart"
            out, err = restarted.communicate(timeout=90)
            assert restarted.returncode == 0, f"restarted PS failed:\n{err[-2000:]}"
            res = json.loads(out.strip().splitlines()[-1])
        finally:
            for p in (ps_proc, restarted):
                if p is not None and p.poll() is None:
                    p.kill()
        assert res["done"] is True
        assert res["accepted"] == 400
        assert res["resumed_from"] is not None and res["resumed_from"] >= 50
        assert sum(counts.values()) > 0
        traj = res["trajectory"]
        assert traj is not None
        # the trajectory spans BOTH lives of the PS (snapshots restored)
        assert len(traj) >= 400 // 50
        assert traj[-1][1] < traj[0][1] * 0.05, traj


@pytest.mark.slow
@pytest.mark.parametrize("algo", ["asgd", "asaga"])
class TestMultiProcess:
    def test_two_worker_processes_converge(self, algo):
        """PS process + 2 worker processes: every gradient crosses a real
        process boundary over loopback TCP, and the run converges to the
        same band as the recipe demands."""
        env_base = dict(os.environ)
        env_base.pop("JAX_PLATFORMS", None)
        env_base.pop("XLA_FLAGS", None)
        env_base["PS_ALGO"] = algo
        if algo == "asaga":
            env_base["PS_GAMMA"] = "0.35"
        env_ps = dict(env_base, PS_ROLE="ps", PS_NUM_WORKER_PROCS="2")
        ps_proc = subprocess.Popen(
            [sys.executable, str(CHILD)], env=env_ps,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            port_line = ps_proc.stdout.readline()
            port = json.loads(port_line)["port"]
            workers = []
            for pid in range(2):
                env_w = dict(
                    env_base, PS_ROLE="worker", PS_PORT=str(port),
                    PS_WORKER_ID=str(pid), PS_NUM_WORKER_PROCS="2",
                )
                workers.append(subprocess.Popen(
                    [sys.executable, str(CHILD)], env=env_w,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                ))
            wresults = []
            for p in workers:
                out, err = p.communicate(timeout=180)
                assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
                wresults.append(json.loads(out.strip().splitlines()[-1]))
            out, err = ps_proc.communicate(timeout=60)
            assert ps_proc.returncode == 0, f"ps failed:\n{err[-2000:]}"
            res = json.loads(out.strip().splitlines()[-1])
        finally:
            for p in [ps_proc] + (workers if "workers" in dir() else []):
                if p.poll() is None:
                    p.kill()
        assert res["done"] is True
        assert res["accepted"] == 400
        # both worker processes actually contributed gradients
        assert all(r["gradients"] > 0 for r in wresults)
        traj = res["trajectory"]
        assert traj is not None
        assert traj[-1][1] < traj[0][1] * 0.05, traj
