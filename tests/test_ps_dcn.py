"""Async parameter server across the process boundary (VERDICT r2 item 3).

The reference's flagship capability is async gradient flow from REMOTE
workers to the driver (CoarseGrainedSchedulerBackend.scala:239-307,
CoarseGrainedExecutorBackend.scala:92).  These tests run the TPU build's
DCN analog (parallel/ps_dcn.py): first fully in-process (protocol logic,
tau filter, cohort waves, convergence), then as REAL separate OS processes
pushing gradients over loopback TCP to a PS process -- the deployment shape
a multi-host v5e pod would use.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.solvers import SolverConfig

CHILD = Path(__file__).parent / "ps_dcn_child.py"


def make_cfg(**kw):
    defaults = dict(
        num_workers=8, num_iterations=300, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.5, printer_freq=50, seed=42,
        calibration_iters=20, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


class TestInProcess:
    def test_converges_and_bookkeeps(self, devices8):
        cfg = make_cfg()
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        shards = {w: ds.shard(w) for w in range(8)}
        counts = ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(8)), shards, cfg, d, n,
            eval_wid=0, deadline_s=120.0,
        )
        assert ps.wait_done(timeout_s=5.0)
        total = ps.collect_eval(num_worker_procs=1, timeout_s=30.0)
        ps.stop()
        assert ps.accepted == cfg.num_iterations
        assert sum(counts.values()) >= cfg.num_iterations
        # staleness is bounded by the total merge count (the logical clock
        # keeps ticking for post-done and dropped pushes)
        assert ps.max_staleness <= ps.accepted + ps.dropped
        assert total is not None
        traj = total / n
        assert traj[-1] < traj[0] * 0.05, traj

    def test_taw_zero_drops_under_overlap(self, devices8):
        cfg = make_cfg(taw=0, num_iterations=150)
        n, d = 2048, 16
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=3, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        shards = {w: ds.shard(w) for w in range(8)}
        ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(8)), shards, cfg, d, n,
            deadline_s=120.0,
        )
        done = ps.wait_done(timeout_s=5.0)
        ps.stop()
        assert done and ps.accepted == 150
        # 8 concurrent pullers against tau=0: overlap must show up as drops
        # unless no overlap ever happened (then max_staleness stayed 0)
        assert ps.dropped > 0 or ps.max_staleness == 0

    def test_cohort_wave_serves_threshold_together(self, devices8):
        """bucket_ratio waves: with threshold 4, pulls are released in
        groups -- the first 3 pullers block until the 4th arrives."""
        cfg = make_cfg(bucket_ratio=0.5, num_iterations=10)
        ps = ps_dcn.ParameterServer(cfg, 8, 800, device=devices8[0],
                                    port=0).start()
        released = []
        lock = threading.Lock()

        def puller(wid):
            cl = ps_dcn.PSClient("127.0.0.1", ps.port)
            got = cl.pull(wid)
            with lock:
                released.append((wid, time.monotonic()))
            cl.bye()
            assert got is not None

        threads = [threading.Thread(target=puller, args=(w,)) for w in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # below the 1s starvation-release fallback
        with lock:
            early = len(released)
        assert early == 0, released
        t4 = threading.Thread(target=puller, args=(3,))
        t4.start()
        for t in threads + [t4]:
            t.join(timeout=10)
        assert len(released) == 4
        ps.stop()


class TestWorkerDeath:
    def test_run_survives_a_killed_worker_group_mid_run(self, devices8):
        """Multi-process fault tolerance: 5 of 8 workers die MID-RUN
        (sockets dropped, no goodbye), leaving 3 survivors -- fewer than
        the cohort threshold of 4 -- so completion additionally proves
        the starvation fallback keeps waves flowing."""
        import threading as th

        cfg = make_cfg(num_iterations=60, bucket_ratio=0.5,
                       printer_freq=20)
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        shards = {w: ds.shard(w) for w in range(3)}

        doomed_stop = th.Event()
        doomed_pushes = {"n": 0}

        def doomed():
            # workers 3..7 participate normally until killed mid-run
            clients = {
                wid: ps_dcn.PSClient("127.0.0.1", ps.port)
                for wid in range(3, 8)
            }
            try:
                while not doomed_stop.is_set():
                    for wid, c in clients.items():
                        got = c.pull(wid)
                        if got is None or doomed_stop.is_set():
                            return
                        ts, _w_host, _avg, _cal = got
                        c.push(wid, ts, np.zeros(d, np.float32))
                        doomed_pushes["n"] += 1
            except (ConnectionError, OSError):
                return
            finally:
                for c in clients.values():
                    try:
                        c.sock.close()  # abrupt death, no BYE
                    except OSError:
                        pass

        t_doomed = th.Thread(target=doomed, daemon=True)
        t_doomed.start()

        survivor_counts = {}

        def survivors():
            survivor_counts.update(ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, list(range(3)), shards, cfg, d, n,
                deadline_s=180.0,
            ))

        t_surv = th.Thread(target=survivors, daemon=True)
        t_surv.start()
        # let the full 8-worker run get underway, then kill the group
        deadline = time.monotonic() + 30
        while doomed_pushes["n"] < 5 and time.monotonic() < deadline:
            time.sleep(0.05)
        doomed_stop.set()
        t_doomed.join(timeout=15)
        assert doomed_pushes["n"] >= 5, "doomed group never participated"

        t_surv.join(timeout=180)
        done = ps.wait_done(timeout_s=30.0)
        ps.stop()
        assert done, "run did not finish after a worker group died mid-run"
        assert ps.accepted == cfg.num_iterations
        assert sum(survivor_counts.values()) > 0


@pytest.mark.slow
class TestMultiProcess:
    def test_two_worker_processes_converge(self):
        """PS process + 2 worker processes: every gradient crosses a real
        process boundary over loopback TCP, and the run converges to the
        same band as the recipe demands."""
        env_base = dict(os.environ)
        env_base.pop("JAX_PLATFORMS", None)
        env_base.pop("XLA_FLAGS", None)
        env_ps = dict(env_base, PS_ROLE="ps", PS_NUM_WORKER_PROCS="2")
        ps_proc = subprocess.Popen(
            [sys.executable, str(CHILD)], env=env_ps,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            port_line = ps_proc.stdout.readline()
            port = json.loads(port_line)["port"]
            workers = []
            for pid in range(2):
                env_w = dict(
                    env_base, PS_ROLE="worker", PS_PORT=str(port),
                    PS_WORKER_ID=str(pid), PS_NUM_WORKER_PROCS="2",
                )
                workers.append(subprocess.Popen(
                    [sys.executable, str(CHILD)], env=env_w,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                ))
            wresults = []
            for p in workers:
                out, err = p.communicate(timeout=180)
                assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
                wresults.append(json.loads(out.strip().splitlines()[-1]))
            out, err = ps_proc.communicate(timeout=60)
            assert ps_proc.returncode == 0, f"ps failed:\n{err[-2000:]}"
            res = json.loads(out.strip().splitlines()[-1])
        finally:
            for p in [ps_proc] + (workers if "workers" in dir() else []):
                if p.poll() is None:
                    p.kill()
        assert res["done"] is True
        assert res["accepted"] == 400
        # both worker processes actually contributed gradients
        assert all(r["gradients"] > 0 for r in wresults)
        traj = res["trajectory"]
        assert traj is not None
        assert traj[-1][1] < traj[0][1] * 0.05, traj
