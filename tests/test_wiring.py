"""End-to-end tests that the sidecar subsystems are wired INTO solver runs.

Round-2 requirement (VERDICT.md item 3): event log + metrics emitted by real
runs, heartbeat-driven executor replacement DURING a run, shard re-homing on
repeated loss, speculation in sync mode, and the versioned-store stale-read
experiment -- each exercised through an actual training run, not a unit
harness.
"""

import threading
import time

import numpy as np
import pytest

from asyncframework_tpu.data import make_regression
from asyncframework_tpu.metrics.eventlog import EventLogReader
from asyncframework_tpu.metrics.report import render_report
from asyncframework_tpu.solvers import ASAGA, ASGD, SolverConfig


@pytest.fixture(scope="module")
def problem():
    return make_regression(2048, 32, seed=3)


def cfg_with(**kw):
    defaults = dict(
        num_workers=8,
        num_iterations=200,
        gamma=0.5,
        taw=2**31 - 1,
        batch_rate=0.3,
        bucket_ratio=0.5,
        printer_freq=50,
        coeff=0.0,
        seed=42,
        calibration_iters=10,
        run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


class TestEventLogWiring:
    def test_asgd_run_emits_event_log_and_metrics(self, devices8, problem, tmp_path):
        X, y, _ = problem
        log = tmp_path / "run.jsonl"
        csv = tmp_path / "metrics.csv"
        cfg = cfg_with(event_log=str(log), metrics_csv=str(csv),
                       metrics_period_s=0.2)
        res = ASGD(X, y, cfg, devices=devices8).run()
        assert res.accepted == 200

        summary = EventLogReader(log).summary()
        assert summary["rounds"] > 0
        assert summary["merges"] >= 200
        assert summary["accepted"] == 200
        # the log's max is over ALL merges; res.max_staleness is the
        # reference's STAT scan (current per-worker values) -- a lower bound
        assert summary["staleness"]["max"] >= res.max_staleness
        # trajectory snapshots flushed at close
        assert len(summary["trajectory"]) == len(res.trajectory)

        # metrics CSV: header + at least one sample (final report guaranteed)
        lines = csv.read_text().strip().splitlines()
        assert len(lines) >= 2
        assert "updates.accepted" in lines[0]

        html = render_report(log, tmp_path / "report.html")
        assert "Summary" in html and "Staleness" in html
        assert (tmp_path / "report.html").exists()

    def test_asaga_run_emits_event_log(self, devices8, problem, tmp_path):
        X, y, _ = problem
        log = tmp_path / "saga.jsonl.gz"
        cfg = cfg_with(num_iterations=100, gamma=0.05, event_log=str(log))
        res = ASAGA(X, y, cfg, devices=devices8).run()
        assert res.accepted == 100
        summary = EventLogReader(log).summary()
        assert summary["accepted"] == 100
        assert summary["rounds"] > 0


class TestFaultToleranceWiring:
    def _run_async_with_kills(self, devices8, problem, kills, cfg):
        """Start an async ASGD run, kill executor 3 `kills` times, return res."""
        X, y, _ = problem
        solver = ASGD(X, y, cfg, devices=devices8)
        out = {}

        def target():
            out["res"] = solver.run()

        t = threading.Thread(target=target)
        t.start()
        try:
            deadline = time.monotonic() + 30
            while not hasattr(solver, "scheduler") and time.monotonic() < deadline:
                time.sleep(0.01)
            assert hasattr(solver, "scheduler"), "run never started"
            for _ in range(kills):
                time.sleep(0.4)  # let some rounds flow
                ex = solver.scheduler.pool.executors[3]
                if ex.alive:
                    ex.kill()
        finally:
            t.join(timeout=120)
        assert not t.is_alive(), "run did not finish"
        return out["res"]

    def test_run_survives_executor_death(self, devices8, problem, tmp_path):
        log = tmp_path / "kill.jsonl"
        cfg = cfg_with(
            num_iterations=1200,
            event_log=str(log),
            heartbeat_timeout_ms=200.0,
            heartbeat_interval_s=0.05,
            max_slot_failures=99,  # transient path only: no re-homing
        )
        res = self._run_async_with_kills(devices8, problem, kills=1, cfg=cfg)
        # the run completed despite the mid-run executor loss
        assert res.accepted == 1200
        assert res.extras.get("workers_lost", 0) >= 1
        summary = EventLogReader(log).summary()
        assert 3 in summary["workers_lost"]
        # convergence still happened
        assert res.trajectory[-1][1] < res.trajectory[0][1]

    def test_repeated_death_rehomes_shard(self, devices8, problem, tmp_path):
        log = tmp_path / "rehome.jsonl"
        cfg = cfg_with(
            num_iterations=2000,
            event_log=str(log),
            heartbeat_timeout_ms=200.0,
            heartbeat_interval_s=0.05,
            max_slot_failures=2,
        )
        res = self._run_async_with_kills(devices8, problem, kills=2, cfg=cfg)
        assert res.accepted == 2000
        assert res.extras.get("workers_lost", 0) >= 2
        assert res.extras.get("shards_moved", 0) >= 1
        # the re-homed shard lives on another worker's device now, and both
        # later rounds and the trajectory evaluation used it successfully
        assert np.isfinite(res.trajectory[-1][1])


class TestSpeculationWiring:
    def test_sync_run_speculates_around_straggler(self, devices8, problem):
        X, y, _ = problem
        cfg = cfg_with(
            num_iterations=40,
            coeff=3.0,            # worker 0 sleeps 3x avg delay per round
            calibration_iters=5,  # calibrate quickly, then inject
            speculation=True,
            speculation_quantile=0.5,
            speculation_multiplier=1.3,
            speculation_min_ms=5.0,
        )
        res = ASGD(X, y, cfg, devices=devices8).run_sync()
        assert res.rounds == 40
        # at least one speculative copy launched and the run completed
        assert res.extras.get("speculated", 0) >= 1

    def test_async_run_speculative_copy_wins(self, devices8, problem):
        """VERDICT r2 weak-6: in ASYNC mode -- where stragglers actually
        matter -- a speculative copy must launch AND claim the slot before
        its delayed primary (the injected delay fires only in the first
        body to run, so the copy takes the healthy path)."""
        X, y, _ = problem
        # timing-based: a loaded CI host can starve the speculative copy's
        # launch window; retry a few times (the assertion is "speculation
        # CAN win", not "wins every time")
        for attempt in range(4):
            cfg = cfg_with(
                num_iterations=150,
                coeff=120.0,          # worker 0 sleeps ~120x avg per round
                calibration_iters=5,
                speculation=True,
                speculation_quantile=0.3,
                speculation_multiplier=1.2,
                speculation_min_ms=10.0,
            )
            res = ASGD(X, y, cfg, devices=devices8).run()
            if res.extras.get("speculation_wins", 0) >= 1:
                break
        assert res.accepted == 150
        assert res.extras.get("speculated", 0) >= 1
        assert res.extras.get("speculation_wins", 0) >= 1


class TestStaleReadWiring:
    def test_stale_read_offset_run(self, devices8, problem):
        X, y, _ = problem
        cfg = cfg_with(num_iterations=200, stale_read_offset=2,
                       max_live_versions=4)
        res = ASGD(X, y, cfg, devices=devices8).run()
        assert res.accepted == 200
        # stale model reads slow convergence but must not break it
        assert res.trajectory[-1][1] < res.trajectory[0][1]


class TestHBMPlanWiring:
    def test_oversized_problem_rejected_with_accounting(self, devices8, problem):
        X, y, _ = problem
        cfg = cfg_with(hbm_budget_bytes=1024)  # absurdly small budget
        with pytest.raises(MemoryError, match="exceeds the"):
            ASGD(X, y, cfg, devices=devices8)
        with pytest.raises(MemoryError, match="exceeds the"):
            ASAGA(X, y, cfg, devices=devices8)

    def test_prebuilt_dataset_residency_measured(self, devices8):
        from asyncframework_tpu.data import SparseShardedDataset, make_sparse_regression

        indptr, indices, values, y = make_sparse_regression(512, 256, 0.05, 0)
        ds = SparseShardedDataset(indptr, indices, values, y, 256, 8, devices8)
        cfg = cfg_with(hbm_budget_bytes=1024)
        with pytest.raises(MemoryError):
            ASGD(ds, None, cfg, devices=devices8)
        # a sane budget accepts the same dataset
        ASGD(ds, None, cfg_with(hbm_budget_bytes=1 << 30), devices=devices8)

    def test_asaga_stale_read_offset_run(self, devices8, problem):
        X, y, _ = problem
        cfg = cfg_with(num_iterations=100, gamma=0.05, stale_read_offset=2)
        res = ASAGA(X, y, cfg, devices=devices8).run()
        assert res.accepted == 100
        assert res.trajectory[-1][1] < res.trajectory[0][1]


class TestDrainBatch:
    def test_batched_drain_run_converges(self, devices8, problem):
        X, y, _ = problem
        cfg = cfg_with(num_iterations=300, drain_batch=8)
        res = ASGD(X, y, cfg, devices=devices8).run()
        assert res.accepted == 300
        assert res.dropped == 0
        assert res.trajectory[-1][1] < res.trajectory[0][1] * 0.5

    def test_batched_drain_checkpoints_across_boundary(self, devices8, problem,
                                                       tmp_path):
        X, y, _ = problem
        cfg = cfg_with(num_iterations=250, drain_batch=8,
                       checkpoint_dir=str(tmp_path / "ck"),
                       checkpoint_freq=100)
        res = ASGD(X, y, cfg, devices=devices8).run()
        assert res.accepted == 250
        from asyncframework_tpu.checkpoint import CheckpointManager

        steps_saved = CheckpointManager(tmp_path / "ck").all_steps()
        # batches jump over k=100/k=200; checkpoints must still exist at or
        # just past every boundary (plus the final save)
        assert len(steps_saved) >= 2
        assert any(100 <= s < 200 for s in steps_saved)
        assert any(200 <= s for s in steps_saved)
