"""Standalone Master/Worker deploy layer (SURVEY §2.4 "Deploy").

Parity coverage: worker registration + heartbeat liveness
(Master.scala:41), executor launch + exit reporting (Worker.scala:43),
app lifecycle states, submission client (StandaloneAppClient.scala:44),
worker-loss detection, and master-restart recovery through the
file persistence engine (ZooKeeperPersistenceEngine.scala:34 role).
"""

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from asyncframework_tpu.deploy import Master, MasterClient, Worker, wait_app

_REPO = Path(__file__).parent.parent
_SPMD_CPU_REASON = None  # session cache: None = not probed, '' = capable


def cpu_spmd_capability() -> str:
    """Probed capability (ISSUE 12 deflake): can THIS rig's jax run a
    2-process SPMD computation on the CPU backend?  jax 0.4.37 without
    gloo-capable CPU collectives raises "Multiprocess computations
    aren't implemented on the CPU backend" -- the same class as the
    documented tests/test_multihost.py baseline failures, but here it
    surfaced as a flaky-looking master-submit failure (supervised
    executor restarts hid the real error).  The probe runs the repo's
    own bring-up (multihost.ensure_initialized + sync_hosts, a
    cross-process pmap psum) in two real subprocesses once per session.
    Returns '' when capable, else the reason to skip with."""
    global _SPMD_CPU_REASON
    if _SPMD_CPU_REASON is not None:
        return _SPMD_CPU_REASON
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = (
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from asyncframework_tpu.parallel import multihost\n"
        "multihost.ensure_initialized(\n"
        "    coordinator_address='127.0.0.1:%d',\n"
        "    num_processes=2, process_id=int(sys.argv[1]))\n"
        "multihost.sync_hosts('probe')\n"
        "print('OK')\n" % port
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(_REPO)
    procs = [subprocess.Popen([sys.executable, "-c", script, str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True,
                              env=env, cwd=str(_REPO))
             for i in range(2)]
    try:
        outs = [p.communicate(timeout=120) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        _SPMD_CPU_REASON = "2-process CPU SPMD probe timed out"
        return _SPMD_CPU_REASON
    if all(p.returncode == 0 for p in procs):
        _SPMD_CPU_REASON = ""
    else:
        err = next((e for (_o, e), p in zip(outs, procs)
                    if p.returncode != 0), "")
        tail = err.strip().splitlines()[-1] if err.strip() else "rc != 0"
        _SPMD_CPU_REASON = f"CPU backend lacks multiprocess SPMD: {tail}"
    return _SPMD_CPU_REASON


@pytest.fixture()
def rig(tmp_path):
    m = Master(persistence_dir=str(tmp_path), worker_timeout_s=2.0).start()
    workers = [
        Worker("127.0.0.1", m.port, worker_id=f"w{i}",
               heartbeat_s=0.3,
               launch_env_extra={"ASYNCTPU_FORCE_CPU": "1",
                                 "JAX_PLATFORMS": "cpu"}).start()
        for i in range(2)
    ]
    yield m, workers
    for w in workers:
        w.stop()
    m.stop()


class TestRegistryAndLiveness:
    def test_register_and_list(self, rig):
        m, _ = rig
        cl = MasterClient("127.0.0.1", m.port)
        ws = cl.workers()
        assert set(ws) == {"w0", "w1"}
        assert all(w["alive"] for w in ws.values())

    def test_worker_loss_detected(self, rig):
        m, workers = rig
        workers[1].stop()
        deadline = time.monotonic() + 10
        cl = MasterClient("127.0.0.1", m.port)
        while time.monotonic() < deadline:
            ws = cl.workers()
            if not ws["w1"]["alive"]:
                break
            time.sleep(0.2)
        assert not cl.workers()["w1"]["alive"]
        assert cl.workers()["w0"]["alive"]

    def test_submit_with_no_workers_rejected(self, tmp_path):
        m = Master(persistence_dir=str(tmp_path)).start()
        try:
            cl = MasterClient("127.0.0.1", m.port)
            with pytest.raises(RuntimeError, match="no alive workers"):
                cl.submit(["--quiet", "asgd"], 2)
        finally:
            m.stop()


class TestAppLifecycle:
    def test_spmd_app_runs_to_finished(self, rig):
        """Capability-gated (ISSUE 13 tier-1 deflake): the 2-process
        sgd-mllib recipe is an SPMD program over a cross-process mesh --
        the same jax-build capability the documented test_multihost
        baseline class needs.  The session-cached probe runs the real
        bring-up once; incapable rigs SKIP with the probed reason
        instead of carrying a permanent baseline failure."""
        reason = cpu_spmd_capability()
        if reason:
            pytest.skip(reason)
        m, _ = rig
        cl = MasterClient("127.0.0.1", m.port)
        # a 2-process SPMD recipe placed by the master: coordinator env is
        # assigned by the scheduler, processes join over jax.distributed
        app_id = cl.submit(
            ["--quiet", "sgd-mllib", "synthetic", "synthetic",
             "16", "512", "4", "20", "1.0", "0", "0.5", "0.5",
             "10", "0", "42"],
            num_processes=2,
        )
        st = wait_app(f"127.0.0.1:{m.port}", app_id, timeout_s=240.0)
        assert st["state"] == "FINISHED", st
        assert len(st["exits"]) == 2
        assert all(rc == 0 for rc in st["exits"].values())

    def test_asgd_ps_app_through_master(self, rig):
        """The full standalone-cluster story: the master schedules a
        3-process DCN asgd app (PS + 2 gradient-pushing workers) across
        its registered worker daemons, and it runs to FINISHED."""
        m, _ = rig
        cl = MasterClient("127.0.0.1", m.port)
        app_id = cl.submit(
            ["--quiet", "asgd", "synthetic", "synthetic",
             "16", "2048", "8", "200", "1.0", "2147483647", "0.3",
             "0.5", "50", "0", "42"],
            num_processes=3,
        )
        st = wait_app(f"127.0.0.1:{m.port}", app_id, timeout_s=240.0)
        assert st["state"] == "FINISHED", st
        assert len(st["exits"]) == 3

    def test_failed_app_reported(self, rig):
        m, _ = rig
        cl = MasterClient("127.0.0.1", m.port)
        app_id = cl.submit(["definitely-not-a-driver"], num_processes=1)
        st = wait_app(f"127.0.0.1:{m.port}", app_id, timeout_s=120.0)
        assert st["state"] == "FAILED"

    def test_kill_app_reclaims_executors(self, rig):
        """KILL_APP terminates the app's executor processes on every
        worker and the app lands in KILLED (not FAILED: the terminations'
        nonzero exits must not relabel it)."""
        m, _ = rig
        cl = MasterClient("127.0.0.1", m.port)
        # 2-process DCN asgd with a huge iteration budget: runs for minutes
        # unless killed
        app_id = cl.submit(
            ["--quiet", "asgd", "synthetic", "synthetic",
             "16", "2048", "8", "5000000", "0.01", "2147483647", "0.3",
             "0.5", "1000", "0", "42"],
            num_processes=2,
        )
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if cl.status(app_id)["state"] == "RUNNING":
                break
            time.sleep(0.2)
        time.sleep(2.0)  # let the executors get properly underway
        reply = cl.kill(app_id)
        assert reply["op"] == "KILLED"
        st = wait_app(f"127.0.0.1:{m.port}", app_id, timeout_s=60.0)
        assert st["state"] == "KILLED"
        # exit reports land asynchronously after the terminations
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = cl.status(app_id)
            if len(st["exits"]) == 2:
                break
            time.sleep(0.2)
        assert len(st["exits"]) == 2  # both executors reported their death
        assert st["state"] == "KILLED"  # nonzero exits did not relabel it


class TestSubmitCLIMasterMode:
    def test_cli_master_submit_waits_to_finished(self, rig, capsys):
        """spark-submit --master parity: the SAME CLI surface ships the
        recipe to the daemon master, waits, and exits 0 on FINISHED.

        Capability-gated (ISSUE 12 deflake): the 2-process sgd-mllib
        recipe is an SPMD program over a cross-process mesh, which this
        rig's CPU backend may not implement (the documented
        test_multihost baseline class).  The probe runs the real
        bring-up once per session; on incapable rigs this SKIPS with
        the probed reason instead of failing as a pseudo-flake."""
        import json as _json

        reason = cpu_spmd_capability()
        if reason:
            pytest.skip(reason)

        from asyncframework_tpu.cli import main as cli_main

        m, _ = rig
        rc = cli_main([
            "--master", f"127.0.0.1:{m.port}", "--processes", "2",
            "--supervise", "--quiet",
            "sgd-mllib", "synthetic", "synthetic",
            "16", "512", "4", "20", "1.0", "0", "0.5", "0.5",
            "10", "0", "42",
        ])
        assert rc == 0
        lines = [l for l in capsys.readouterr().out.splitlines()
                 if l.startswith("{")]
        sub = _json.loads(lines[0])
        fin = _json.loads(lines[-1])
        assert sub["supervise"] is True and sub["num_processes"] == 2
        assert fin["state"] == "FINISHED"
        assert all(rc == 0 for rc in fin["exits"].values())
        # the master recorded the supervise flag on the app
        assert m.apps[sub["app_id"]]["supervise"] is True


class TestMasterUI:
    def test_status_page_and_api(self, tmp_path):
        import json as _json
        import urllib.request

        from asyncframework_tpu.deploy import Master, Worker

        m = Master(persistence_dir=str(tmp_path), ui_port=0).start()
        w = Worker("127.0.0.1", m.port, worker_id="w0",
                   heartbeat_s=0.3).start()
        try:
            base = f"http://127.0.0.1:{m._ui.port}"
            with urllib.request.urlopen(base + "/api/status", timeout=5) as r:
                st = _json.loads(r.read())
            assert st["active"] is True
            assert "w0" in st["workers"]
            with urllib.request.urlopen(base + "/", timeout=5) as r:
                html = r.read().decode()
            assert "async master" in html
        finally:
            w.stop()
            m.stop()

    def test_ui_host_is_configurable(self, tmp_path):
        """ISSUE 1 satellite: the UI used to hard-bind 127.0.0.1 -- a k8s
        Service could never route to it.  ``ui_host`` must reach the HTTP
        server's actual bind address."""
        from asyncframework_tpu.deploy import Master

        m = Master(persistence_dir=str(tmp_path), ui_port=0,
                   ui_host="0.0.0.0").start()
        try:
            assert m._ui._httpd.server_address[0] == "0.0.0.0"
            # still reachable over loopback (0.0.0.0 covers it)
            import urllib.request

            with urllib.request.urlopen(
                f"http://127.0.0.1:{m._ui.port}/api/status", timeout=5
            ) as r:
                assert r.status == 200
        finally:
            m.stop()


class TestMasterRecovery:
    def test_state_survives_master_restart(self, tmp_path):
        m = Master(persistence_dir=str(tmp_path), worker_timeout_s=2.0).start()
        w = Worker("127.0.0.1", m.port, worker_id="w0",
                   heartbeat_s=0.3).start()
        cl = MasterClient("127.0.0.1", m.port)
        assert "w0" in cl.workers()
        port = m.port
        m.stop()
        time.sleep(0.2)
        # new master on the SAME port recovers the registry from disk;
        # the worker's heartbeat (or RECONNECT reply) re-validates it.
        # The old listener can take a beat to release the port under a
        # loaded host -- retry the rebind briefly (real restarts do too).
        deadline = time.monotonic() + 10
        while True:
            try:
                m2 = Master(port=port, persistence_dir=str(tmp_path),
                            worker_timeout_s=2.0).start()
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.3)
        try:
            cl2 = MasterClient("127.0.0.1", m2.port)
            ws = cl2.workers()
            assert "w0" in ws  # recovered from the persistence engine
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if cl2.workers()["w0"]["alive"]:
                    break
                time.sleep(0.2)
            assert cl2.workers()["w0"]["alive"]  # re-validated by heartbeat
        finally:
            w.stop()
            m2.stop()

    def test_running_apps_marked_lost_on_recovery(self, tmp_path):
        state = {
            "workers": {},
            "apps": {"app-0001": {
                "argv": ["x"], "env": {}, "num_processes": 2,
                "state": "RUNNING",
            }},
            "app_seq": 1,
        }
        with open(f"{tmp_path}/master-state.json", "w") as f:
            json.dump(state, f)
        m2 = Master(persistence_dir=str(tmp_path)).start()
        try:
            cl = MasterClient("127.0.0.1", m2.port)
            assert cl.status("app-0001")["state"] == "LOST"
        finally:
            m2.stop()


@pytest.mark.slow
class TestStandbyFailover:
    def test_kill_active_master_standby_takes_over_app_finishes(
        self, tmp_path
    ):
        """VERDICT r3 item 9, the exact done-criterion: kill the active
        master mid-app; the standby wins the flock lease, recovers state
        from the shared persistence dir (RUNNING stays RUNNING -- the
        executors belong to live worker daemons), workers rotate their
        heartbeats to it, and the app runs to FINISHED."""
        import signal
        import subprocess
        import sys

        # active master: a real OS process, so SIGKILL exercises the
        # kernel's automatic flock release (the lease's whole point)
        active = subprocess.Popen(
            [sys.executable, "-m", "asyncframework_tpu.deploy.master",
             "--port", "0", "--persistence-dir", str(tmp_path), "--ha"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        standby = None
        workers = []
        try:
            line = active.stdout.readline()
            active_addr = line.split()[-2 if "(ha)" in line else -1]
            a_host, a_port = active_addr.rsplit(":", 1)

            from asyncframework_tpu.deploy.client import (
                MasterClient as MC,
                _client as _client_for,
            )

            # wait for the active master to win the lease and serve
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    MC(a_host, int(a_port)).workers()
                    break
                except (ConnectionError, OSError):
                    time.sleep(0.1)

            standby = Master(persistence_dir=str(tmp_path),
                             worker_timeout_s=2.0, ha=True).start()
            # standby must refuse service while the active master lives
            with pytest.raises(ConnectionError):
                MC("127.0.0.1", standby.port).workers()

            workers = [
                Worker(a_host, int(a_port), worker_id=f"w{i}",
                       heartbeat_s=0.3,
                       standby_masters=[f"127.0.0.1:{standby.port}"],
                       launch_env_extra={"ASYNCTPU_FORCE_CPU": "1",
                                         "JAX_PLATFORMS": "cpu"}).start()
                for i in range(2)
            ]
            ha_addr = f"{active_addr},127.0.0.1:{standby.port}"
            cl = _client_for(ha_addr)
            # long enough to straddle the failover: 2-process DCN asgd
            app_id = cl.submit(
                ["--quiet", "asgd", "synthetic", "synthetic",
                 "16", "2048", "8", "20000", "0.05", "2147483647", "0.3",
                 "0.5", "1000", "0", "42"],
                num_processes=2,
            )
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if cl.status(app_id)["state"] == "RUNNING":
                    break
                time.sleep(0.2)
            assert cl.status(app_id)["state"] == "RUNNING"
            time.sleep(1.0)  # executors underway

            active.send_signal(signal.SIGKILL)
            active.wait(timeout=10)

            # the standby must take over and report the app still RUNNING
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not standby.active:
                time.sleep(0.1)
            assert standby.active, "standby never won the lease"
            assert cl.status(app_id)["state"] == "RUNNING"

            st = wait_app(ha_addr, app_id, timeout_s=240.0)
            assert st["state"] == "FINISHED", st
            assert len(st["exits"]) == 2
            assert all(rc == 0 for rc in st["exits"].values())
            # workers rotated: the standby sees them alive
            ws = cl.workers()
            assert set(ws) == {"w0", "w1"}
        finally:
            for w in workers:
                w.stop()
            if standby is not None:
                standby.stop()
            if active.poll() is None:
                active.kill()


class TestExitPersistence:
    def test_partial_exits_survive_recovery(self, tmp_path):
        """An executor exit ACKed before a master death must be found on
        disk by the successor -- the worker never resends it."""
        m = Master(persistence_dir=str(tmp_path)).start()
        try:
            with m._lock:
                m.apps["app-0001"] = {
                    "argv": ["x"], "env": {}, "num_processes": 2,
                    "state": "RUNNING", "assignments": [], "exits": {},
                }
                m._persist()
            reply = m._handle({"op": "EXECUTOR_EXIT", "worker_id": "w0",
                               "app_id": "app-0001", "proc_id": 0,
                               "returncode": 0})
            assert reply["op"] == "ACK"
        finally:
            m.stop()
        m2 = Master(persistence_dir=str(tmp_path)).start()
        try:
            # cold restart marks it LOST but the partial exit is retained;
            # the second exit then completes the count
            assert m2.apps["app-0001"]["exits"] == {"0": 0}
        finally:
            m2.stop()


class TestSingleProcessApp:
    def test_one_process_asgd_runs_plain(self, rig):
        """A 1-process asgd placement gets coordinator env from the master
        but must run as a normal single-process solver (DCN mode needs
        peers)."""
        m, _ = rig
        cl = MasterClient("127.0.0.1", m.port)
        app_id = cl.submit(
            ["--quiet", "asgd", "synthetic", "synthetic",
             "16", "1024", "4", "100", "1.0", "2147483647", "0.3",
             "0.5", "50", "0", "42"],
            num_processes=1,
        )
        st = wait_app(f"127.0.0.1:{m.port}", app_id, timeout_s=240.0)
        assert st["state"] == "FINISHED", st
