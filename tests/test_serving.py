"""Serving tier (ISSUE 6): snapshot-subscribing predict replicas with
freshness-lag SLOs.

The correctness spine:

- a replica's served model is ALWAYS a version the PS actually published:
  refreshes ride the CRC-gated delta-pull machinery (NM/XDELTA/FULL with
  full-pull fallback), the served reference swaps atomically, and seeded
  chaos on the SUBSCRIBE stream (drop_reply / cut_mid_frame) can delay a
  refresh but never tear a model;
- PREDICT replies are stamped with the served version and its freshness
  lag (versions + ms); a replica past the staleness SLO answers
  UNHEALTHY and the frontend fails over -- unless the run is DONE and
  the replica holds the final version (fresh forever by construction);
- the frontend's rotation survives replica death: a real kill -9 of a
  replica OS process mid-load degrades to failover, never an outage,
  and the PR 2 membership machinery (adopt=False mode) declares the
  corpse dead by pid probe.
"""

import os
import signal
import socket as socket_mod
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.conf import set_global_conf
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.metrics import reset_totals
from asyncframework_tpu.metrics.live import LiveStateListener
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net import faults
from asyncframework_tpu.net.faults import (
    CUT_MID_FRAME,
    DROP_REPLY,
    FaultSchedule,
)
from asyncframework_tpu.net.retry import reset_breakers
from asyncframework_tpu.ops import steps
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.serving import (
    ModelReplica,
    PredictError,
    ServingFrontend,
)
from asyncframework_tpu.serving.replica import serve_replica
from asyncframework_tpu.serving import metrics as smetrics
from asyncframework_tpu.solvers import SolverConfig

pytestmark = pytest.mark.serve

REPO = Path(__file__).parent.parent
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


def make_cfg(**kw):
    defaults = dict(
        num_workers=2, num_iterations=40, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=10, seed=42,
        calibration_iters=4, run_timeout_s=60.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    """Serving totals, fault schedules, and endpoint breakers are
    process-global; tests must neither inherit nor leak them."""
    reset_totals()
    reset_breakers()
    faults.clear()
    yield
    reset_totals()
    reset_breakers()
    faults.clear()
    set_global_conf(None)


def start_ps(devices, cfg=None, d=16, n=256):
    cfg = cfg or make_cfg()
    ps = ps_dcn.ParameterServer(cfg, d, n, device=devices[0],
                                port=0).start()
    return ps, cfg, d, n


def push_once(cl, wid, d, scale=1.0):
    """One pull+push through a FULL-mode client: advances the model by a
    known gradient (taw=inf, so it always lands)."""
    ts, _w, _avg, _cal = cl.pull(wid)
    cl.push(wid, ts, np.full(d, scale, np.float32))


def predict_direct(port: int, X: np.ndarray):
    """One raw PREDICT frame against a replica (no frontend)."""
    X = np.ascontiguousarray(X, np.float32)
    sock = _frame.connect(("127.0.0.1", port))
    try:
        _frame.send_msg(sock, {"op": "PREDICT", "n": X.shape[0]},
                        X.tobytes())
        return _frame.recv_msg(sock)
    finally:
        sock.close()


# -------------------------------------------------------------- predict op
class TestPredictStep:
    def test_matches_numpy(self, rng):
        X = rng.normal(size=(32, 16)).astype(np.float32)
        w = rng.normal(size=16).astype(np.float32)
        y = np.asarray(steps.make_predict_step("least_squares")(X, w))
        np.testing.assert_allclose(y, X @ w, rtol=1e-5, atol=1e-5)
        p = np.asarray(steps.make_predict_step("logistic")(X, w))
        np.testing.assert_allclose(p, 1.0 / (1.0 + np.exp(-(X @ w))),
                                   rtol=1e-5, atol=1e-5)

    def test_unknown_loss_rejected(self):
        with pytest.raises(ValueError):
            steps.make_predict_step("hinge")


# ------------------------------------------------------------ replica core
class TestReplicaRefresh:
    def test_refresh_matches_direct_pull_at_same_version(self, devices8,
                                                         rng):
        """THE correctness claim: what the replica serves is byte-for-byte
        what a direct PS pull returns at the same version."""
        ps, cfg, d, n = start_ps(devices8)
        rep = None
        try:
            pusher = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="full")
            for i in range(5):
                push_once(pusher, 0, d, scale=0.1 * (i + 1))
            rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=999.0).start()
            assert rep.refresh_once()
            served = rep._served
            direct = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="delta").subscribe(9)
            ts, w, clock, k, _age, _done = direct
            assert served.ts == ts == clock and k == 5
            assert served.w_host.tobytes() == w.tobytes()
            # and the wire PREDICT agrees with the math
            X = rng.normal(size=(8, d)).astype(np.float32)
            hdr, payload = predict_direct(rep.port, X)
            assert hdr["op"] == "PREDICTION" and hdr["ts"] == ts
            y = np.frombuffer(payload, np.float32)
            np.testing.assert_allclose(y, X @ w, rtol=1e-5, atol=1e-5)
        finally:
            if rep is not None:
                rep.stop()
            ps.stop()

    def test_refresh_shapes_nm_then_full_on_change(self, devices8):
        """Steady state is a header-only NOT_MODIFIED; a changed model
        re-syncs via delta/full -- the PR 4 cache-invalidation protocol
        doing replica duty."""
        ps, cfg, d, n = start_ps(devices8)
        rep = None
        try:
            rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=999.0).start()
            assert rep.refresh_once()  # first: full (no basis)
            assert rep.refresh_once()  # unchanged: NM
            # >=: the background loop's own first refresh also counts
            assert rep._client.pull_wenc["nm"] >= 1
            pusher = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="full")
            push_once(pusher, 0, d)
            assert rep.refresh_once()
            assert (rep._client.pull_wenc["full"]
                    + rep._client.pull_wenc["xdelta"] >= 2)
            assert rep._served.ts == ps._clock
            # NM replies cost zero model payload on the PS side
            assert ps.subscribe_replies["nm"] >= 1
        finally:
            if rep is not None:
                rep.stop()
            ps.stop()

    def test_crc_mismatch_falls_back_to_full_pull(self, devices8):
        """A corrupted basis can never be served: the next NM/delta
        decode fails its CRC and the client re-pulls FULL."""
        ps, cfg, d, n = start_ps(devices8)
        rep = None
        try:
            rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=999.0).start()
            assert rep.refresh_once()
            cl = rep._client
            ts, w, crc = cl._basis[0]
            cl._basis[0] = (ts, w, crc ^ 0xDEADBEEF)  # poison the CRC
            assert rep.refresh_once()
            assert cl.delta_fallbacks == 1
            direct = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="delta").subscribe(9)
            assert rep._served.w_host.tobytes() == direct[1].tobytes()
            assert smetrics.serving_totals().get("refresh_fallbacks") == 1
        finally:
            if rep is not None:
                rep.stop()
            ps.stop()


# ---------------------------------------------------------- freshness lag
class TestFreshnessLag:
    def test_version_age_on_ps(self, devices8):
        """age_ms(ts) is 0 while ts is still the served content (dropped
        pushes tick the clock without changing the model) and grows once
        a newer version is published."""
        ps, cfg, d, n = start_ps(devices8)
        try:
            pusher = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="full")
            push_once(pusher, 0, d)
            c = ps._clock
            assert ps._version_age_ms(c, c) == 0.0
            time.sleep(0.05)
            push_once(pusher, 0, d)
            age = ps._version_age_ms(c, ps._clock)
            assert age > 0.0
        finally:
            ps.stop()

    def test_reply_lag_fields(self, devices8, rng):
        ps, cfg, d, n = start_ps(devices8)
        rep = None
        try:
            rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=999.0).start()
            assert rep.refresh_once()
            hdr, _ = predict_direct(rep.port,
                                    rng.normal(size=(2, d)).astype(
                                        np.float32))
            assert hdr["lag_versions"] == 0
            assert hdr["lag_ms"] >= 0.0
            assert hdr["ts"] == rep._served.ts
        finally:
            if rep is not None:
                rep.stop()
            ps.stop()

    def test_unhealthy_past_staleness_slo_and_recovery(self, devices8,
                                                       rng):
        """A replica whose refresh is older than the SLO answers
        UNHEALTHY (the frontend raises once NOBODY is healthy); the next
        successful refresh restores it."""
        ps, cfg, d, n = start_ps(devices8)
        rep = fe = None
        try:
            rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=999.0,
                               max_stale_ms=120.0).start()
            assert rep.refresh_once()
            assert rep.healthy()
            fe = ServingFrontend([("127.0.0.1", rep.port)],
                                 deadline_s=0.4).start()
            X = rng.normal(size=(2, d)).astype(np.float32)
            fe.predict(X)  # fresh: answers
            time.sleep(0.3)  # blow the 120 ms SLO
            assert not rep.healthy()
            with pytest.raises(PredictError):
                fe.predict(X)
            assert smetrics.serving_totals()["unhealthy_rejects"] > 0
            assert rep.refresh_once()  # refresh lands: healthy again
            fe.predict(X)
        finally:
            if fe is not None:
                fe.stop()
            if rep is not None:
                rep.stop()
            ps.stop()

    def test_done_run_is_fresh_forever(self, devices8, rng):
        """Training DONE + final version held => the model can never
        change again: the replica stays healthy with the PS gone (reads
        outlive the training plane)."""
        cfg = make_cfg(num_iterations=20)
        ps, cfg, d, n = start_ps(devices8, cfg)
        rep = None
        try:
            ds = ShardedDataset.generate_on_device(
                n, d, cfg.num_workers, devices=devices8[:2], seed=11,
                noise=0.01,
            )
            ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, list(range(cfg.num_workers)),
                {w: ds.shard(w) for w in range(cfg.num_workers)},
                cfg, d, n, deadline_s=60.0,
            )
            assert ps.wait_done(timeout_s=10.0)
            rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=999.0,
                               max_stale_ms=100.0).start()
            assert rep.refresh_once()
            served = rep._served
            assert served.done and served.ts >= served.clock
            ps.stop()
            time.sleep(0.25)  # way past the SLO; done-exemption holds
            assert rep.healthy()
            hdr, _ = predict_direct(
                rep.port, rng.normal(size=(2, d)).astype(np.float32)
            )
            assert hdr["op"] == "PREDICTION"
            assert hdr["lag_versions"] == 0 and hdr["lag_ms"] == 0.0
        finally:
            if rep is not None:
                rep.stop()
            ps.stop()


# ------------------------------------------------------------------ chaos
class TestServingChaos:
    def test_subscribe_chaos_never_serves_a_torn_model(self, devices8):
        """Seeded drop_reply / cut_mid_frame on the SUBSCRIBE stream: the
        retry layer re-pulls, the CRC gate discards anything suspect, and
        every model the replica EVER serves is byte-for-byte a version
        the PS actually published."""
        ps, cfg, d, n = start_ps(devices8)
        rep = None
        try:
            pusher = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="full")
            rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=999.0).start()
            assert rep.refresh_once()  # clean first sync
            versions = {}  # ts -> published bytes, harvested via PULL
            sched = FaultSchedule(seed=CHAOS_SEED)
            sched.add("*", "SUBSCRIBE", 1, DROP_REPLY)
            sched.add("*", "SUBSCRIBE", 3, CUT_MID_FRAME)
            sched.add("*", "SUBSCRIBE", 5, DROP_REPLY)
            with faults.injected(sched) as inj:
                for i in range(6):
                    push_once(pusher, 0, d, scale=0.1 * (i + 1))
                    ts, w, _avg, _cal = pusher.pull(0)
                    versions[ts] = w.tobytes()
                    if rep.refresh_once():
                        served = rep._served
                        assert served.ts in versions
                        assert (served.w_host.tobytes()
                                == versions[served.ts]), \
                            "torn model served after wire fault"
                assert inj.fired, "schedule never fired"
            # post-chaos: one clean refresh converges on the live version
            assert rep.refresh_once()
            ts, w, *_rest = ps_dcn.PSClient(
                "127.0.0.1", ps.port, pull_mode="delta"
            ).subscribe(9)
            assert rep._served.ts == ts
            assert rep._served.w_host.tobytes() == w.tobytes()
        finally:
            if rep is not None:
                rep.stop()
            ps.stop()

    def test_predict_chaos_and_dead_replica_failover(self, devices8, rng):
        """drop_reply on a PREDICT is retried/failed over transparently;
        a stopped replica drops out of rotation and the frontend keeps
        answering from the survivor."""
        ps, cfg, d, n = start_ps(devices8)
        rep_a = rep_b = fe = None
        try:
            rep_a = ModelReplica("127.0.0.1", ps.port, rid=0,
                                 host="127.0.0.1",
                                 refresh_interval_s=999.0).start()
            rep_b = ModelReplica("127.0.0.1", ps.port, rid=1,
                                 host="127.0.0.1",
                                 refresh_interval_s=999.0).start()
            assert rep_a.refresh_once() and rep_b.refresh_once()
            fe = ServingFrontend(
                [("127.0.0.1", rep_a.port), ("127.0.0.1", rep_b.port)],
                deadline_s=2.0,
            ).start()
            X = rng.normal(size=(4, d)).astype(np.float32)
            expect = X @ np.asarray(rep_a._served.w_host)
            sched = FaultSchedule(seed=CHAOS_SEED)
            sched.add("*", "PREDICT", 1, DROP_REPLY)
            sched.add("*", "PREDICT", 2, CUT_MID_FRAME)
            with faults.injected(sched) as inj:
                for _ in range(4):
                    y = fe.predict(X)
                    np.testing.assert_allclose(y, expect, rtol=1e-5,
                                               atol=1e-5)
                assert inj.fired
            # now lose a replica outright: rotation degrades, answers don't
            rep_a.stop()
            for _ in range(4):
                y, meta = fe.predict_ex(X)
                np.testing.assert_allclose(y, expect, rtol=1e-5,
                                           atol=1e-5)
                assert meta["endpoint"].endswith(str(rep_b.port))
        finally:
            if fe is not None:
                fe.stop()
            for r in (rep_a, rep_b):
                if r is not None:
                    r.stop()
            ps.stop()


# --------------------------------------------- kill -9 acceptance (2 proc)
class TestKillNineAcceptance:
    def test_sigkill_replica_mid_load_frontend_keeps_answering(
            self, devices8, rng, tmp_path):
        """THE acceptance test: two REAL replica OS processes register
        with the frontend via HELLO; one is SIGKILLed mid-load; every
        client request keeps being answered (failover within the
        deadline, zero client-visible errors) and the membership
        machinery declares the corpse dead by pid probe."""
        cfg = make_cfg(num_iterations=10_000)
        ps, cfg, d, n = start_ps(devices8)
        fe = None
        procs = []
        try:
            fe = ServingFrontend(deadline_s=3.0).serve(port=0,
                                                       host="127.0.0.1")
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["ASYNCTPU_FORCE_CPU"] = "1"
            env["PYTHONPATH"] = str(REPO)
            env["ASYNCTPU_ASYNC_SERVE_REFRESH_INTERVAL_S"] = "0.02"
            for rid in range(2):
                procs.append(subprocess.Popen(
                    [sys.executable, "-m",
                     "asyncframework_tpu.serving.cli", "replica",
                     "--ps", f"127.0.0.1:{ps.port}",
                     "--host", "127.0.0.1", "--rid", str(rid),
                     "--frontend", f"127.0.0.1:{fe.port}"],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    env=env, cwd=str(REPO), text=True,
                ))
            deadline = time.monotonic() + 90.0
            while fe.replica_count() < 2:
                assert time.monotonic() < deadline, \
                    "replicas never registered"
                time.sleep(0.1)
            # light training keeps versions moving under the load
            pusher = ps_dcn.PSClient("127.0.0.1", ps.port,
                                     pull_mode="full")
            X = rng.normal(size=(4, d)).astype(np.float32)
            answered = 0
            endpoints = set()
            for i in range(60):
                if i == 20:
                    os.kill(procs[0].pid, signal.SIGKILL)
                if i % 10 == 0:
                    push_once(pusher, 0, d, scale=0.05)
                y, meta = fe.predict_ex(X)  # must NEVER raise
                assert y.shape == (4,)
                answered += 1
                endpoints.add(meta["endpoint"])
                time.sleep(0.01)
            assert answered == 60
            assert len(endpoints) == 2  # both replicas served pre-kill
            # the pid probe (HELLO carried pid+host) declares the corpse
            member_deadline = time.monotonic() + 10.0
            while time.monotonic() < member_deadline:
                states = [m.get("state")
                          for m in fe.membership().values()]
                if "dead" in states:
                    break
                time.sleep(0.2)
            assert "dead" in [m.get("state")
                              for m in fe.membership().values()]
            assert smetrics.serving_totals().get("failovers", 0) >= 1
        finally:
            if fe is not None:
                fe.stop()
            for p in procs:
                try:
                    p.kill()
                except OSError:
                    pass
            ps.stop()


# ------------------------------------------------------ frontend mechanics
class TestFrontend:
    def test_round_robin_spreads_load(self, devices8, rng):
        ps, cfg, d, n = start_ps(devices8)
        rep_a = rep_b = fe = None
        try:
            rep_a = ModelReplica("127.0.0.1", ps.port, rid=0,
                                 host="127.0.0.1",
                                 refresh_interval_s=999.0).start()
            rep_b = ModelReplica("127.0.0.1", ps.port, rid=1,
                                 host="127.0.0.1",
                                 refresh_interval_s=999.0).start()
            assert rep_a.refresh_once() and rep_b.refresh_once()
            fe = ServingFrontend(
                [("127.0.0.1", rep_a.port), ("127.0.0.1", rep_b.port)],
                deadline_s=2.0,
            ).start()
            X = rng.normal(size=(2, d)).astype(np.float32)
            seen = [fe.predict_ex(X)[1]["endpoint"] for _ in range(6)]
            assert len(set(seen)) == 2  # both replicas take traffic
        finally:
            if fe is not None:
                fe.stop()
            for r in (rep_a, rep_b):
                if r is not None:
                    r.stop()
            ps.stop()

    def test_reregistration_is_idempotent(self):
        fe = ServingFrontend(deadline_s=0.1)
        try:
            a = fe.add_replica("127.0.0.1", 12345)
            b = fe.add_replica("127.0.0.1", 12345)
            assert a == b and fe.replica_count() == 1
            assert smetrics.serving_totals()["replicas_registered"] == 1
        finally:
            fe.stop()

    def test_dead_slot_reclaimed_at_capacity(self):
        """Replica churn hands every replacement a fresh endpoint: at
        capacity a DEAD slot is reclaimed, never a permanent refusal."""
        fe = ServingFrontend(deadline_s=0.1, max_replicas=2,
                             dead_after_s=0.15)
        try:
            # pid 2^22+1 is beyond pid_max on this box: the local-pid
            # probe declares the slot's proc exited on the first scan
            fe.add_replica("127.0.0.1", 11111, pid=4_194_305,
                           hostname=socket_mod.gethostname())
            fe.add_replica("127.0.0.1", 11112)
            with pytest.raises(ValueError):
                fe.add_replica("127.0.0.1", 11113)  # full, nobody dead
            time.sleep(0.25)  # both slots silent past dead_after
            fe.supervisor.check_once()
            idx = fe.add_replica("127.0.0.1", 11113)
            assert idx in (0, 1)
            assert "127.0.0.1:11113" in fe.membership()
            assert fe.replica_count() == 2
        finally:
            fe.stop()

    def test_replica_rehello_survives_frontend_restart(self, devices8):
        """HELLO is a heartbeat loop: a restarted frontend (same
        address, as behind a k8s Service) rebuilds its rotation from the
        replicas' next beats -- no replica restart required."""
        ps, cfg, d, n = start_ps(devices8)
        rep = fe = fe2 = None
        try:
            fe = ServingFrontend(deadline_s=1.0).serve(port=0,
                                                       host="127.0.0.1")
            port0 = fe.port
            rep = serve_replica(f"127.0.0.1:{ps.port}", rid=0,
                                host="127.0.0.1",
                                frontend=f"127.0.0.1:{port0}",
                                announce=lambda *a, **k: None,
                                hello_interval_s=0.1)
            deadline = time.monotonic() + 10.0
            while fe.replica_count() < 1:
                assert time.monotonic() < deadline
                time.sleep(0.05)
            fe.stop()
            # rebind the same address (a restarting daemon retries while
            # the old instance's sockets drain)
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    fe2 = ServingFrontend(deadline_s=1.0).serve(
                        port=port0, host="127.0.0.1"
                    )
                    break
                except OSError:
                    assert time.monotonic() < deadline
                    time.sleep(0.1)
            assert fe2.replica_count() == 0  # fresh process state
            deadline = time.monotonic() + 10.0
            while fe2.replica_count() < 1:
                assert time.monotonic() < deadline, \
                    "replica never re-registered with restarted frontend"
                time.sleep(0.05)
        finally:
            for f in (fe, fe2):
                if f is not None:
                    f.stop()
            if rep is not None:
                rep.stop()
            ps.stop()

    def test_frontdoor_hello_and_predict_proxy(self, devices8, rng):
        """The daemon face: a replica HELLOs the front door in, a client
        PREDICT frame is proxied through the rotation."""
        ps, cfg, d, n = start_ps(devices8)
        rep = fe = None
        try:
            rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                               host="127.0.0.1",
                               refresh_interval_s=999.0).start()
            assert rep.refresh_once()
            fe = ServingFrontend(deadline_s=2.0).serve(port=0,
                                                       host="127.0.0.1")
            sock = _frame.connect(("127.0.0.1", fe.port))
            _frame.send_msg(sock, {"op": "HELLO", "replica": True,
                                   "proc": "t-rep", "port": rep.port,
                                   "host": socket_mod.gethostname(),
                                   "pid": os.getpid()})
            hdr, _ = _frame.recv_msg(sock)
            assert hdr["op"] == "WELCOME"
            X = rng.normal(size=(3, d)).astype(np.float32)
            _frame.send_msg(sock, {"op": "PREDICT", "n": 3}, X.tobytes())
            hdr, payload = _frame.recv_msg(sock)
            assert hdr["op"] == "PREDICTION"
            y = np.frombuffer(payload, np.float32)
            np.testing.assert_allclose(
                y, X @ np.asarray(rep._served.w_host), rtol=1e-5,
                atol=1e-5,
            )
            sock.close()
        finally:
            if fe is not None:
                fe.stop()
            if rep is not None:
                rep.stop()
            ps.stop()


# ---------------------------------------------------- counters (satellite)
class TestServingCounters:
    def test_reset_totals_zeroes_serving(self):
        smetrics.bump("predicts", 3)
        smetrics.observe_predict("x:1", 1.0, 2, 30.0, 5)
        assert smetrics.serving_totals()["predicts"] == 4
        reset_totals()
        assert smetrics.serving_totals() == {}
        assert smetrics.serving_snapshot()["predict_ms"] == {"count": 0}

    def test_live_ui_second_run_starts_at_zero(self):
        """The PR 3 bug class, serving edition: a listener built for a
        second run must not inherit the first run's QPS/lag totals."""
        smetrics.bump("predicts", 10)
        smetrics.bump("failovers", 2)
        listener = LiveStateListener(2)  # second run starts HERE
        snap = listener.snapshot()["serving"]
        assert snap["predicts"] == 0 and snap["failovers"] == 0
        smetrics.bump("predicts", 5)
        assert listener.snapshot()["serving"]["predicts"] == 5
        # the raw detail view still carries the process totals
        assert listener.snapshot()["serving"]["detail"]["predicts"] == 15
