"""Native wire data plane (ISSUE 19): property suites proving every
native fast path is a pure *optimization*.

- bit-identity: each ctypes entry point in ``net/wiredelta.py``,
  ``net/wirecodec.py``, and ``net/frame.py`` produces byte-identical
  output to its registered pure-Python oracle, including the unfriendly
  floats (NaN payload bits, +/-inf, -0.0, subnormals) and degenerate
  shapes (empty, single element, odd lengths);
- cross-backend ring: the shm ring layout is the contract, not the
  code -- every writer-backend x reader-backend combination moves the
  same bytes through the same segment, EOF flags included;
- transport integration: a real SHM_OPEN handshake upgrades a loopback
  TCP connection and frames round-trip over the rings; a SIGKILL'd
  peer degrades with ``ConnectionError`` (never a hang) and is counted;
- toolchain-absent: no compiler means probed skips for the identity
  suites, a ``no-toolchain`` --check report, and a visible
  ``python_fallbacks`` bump when native was wanted but unavailable;
- ``native-oracle`` lint: each direction of the rule fires on a minimal
  mutated fixture and the real tree lints clean.

The native-requiring tests skip as a unit when ``ensure_built`` cannot
produce the libraries (the PR 12 probed-skip discipline: the skip names
the missing capability, and boxes with a toolchain run everything).
"""

import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import zlib

import numpy as np
import pytest

from asyncframework_tpu import conf as conf_mod
from asyncframework_tpu import native_build
from asyncframework_tpu.analysis import rules_native
from asyncframework_tpu.analysis.core import LintContext, run_lint
from asyncframework_tpu.native_build import ensure_built, native_totals
from asyncframework_tpu.net import frame, shmring, wirecodec, wiredelta
from asyncframework_tpu.net.shmring import ShmRing, ShmSocket

pytestmark = pytest.mark.native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NATIVE_OK = all(
    ensure_built(n) is not None
    for n in ("wiredelta", "wirecodec", "shmring"))
needs_native = pytest.mark.skipif(
    not NATIVE_OK, reason="no C++ toolchain (wire natives not built)")


@pytest.fixture()
def cf():
    """The global conf with full store save/restore (these tests flip
    the native/shm knobs; nothing may leak into later suites)."""
    c = conf_mod.global_conf()
    saved = dict(c._store)
    yield c
    c._store.clear()
    c._store.update(saved)


def both(cf, fn):
    """Run ``fn`` once per backend; returns (python_result, native_result)."""
    cf.set("async.native.enabled", False)
    py = fn()
    cf.set("async.native.enabled", True)
    nat = fn()
    return py, nat


# ------------------------------------------------------- model vectors
def _vectors():
    """(cur, basis) float32 pairs spanning every wire form and the
    unfriendly bit patterns."""
    rng = np.random.default_rng(19)
    out = []
    base = rng.standard_normal(513).astype(np.float32)
    out.append(("nm", base, base.copy()))
    sparse = base.copy()
    sparse[[0, 7, 500]] += np.float32(1.0)
    out.append(("xdelta", sparse, base))
    dense = (base + rng.standard_normal(513).astype(np.float32))
    out.append(("full", dense, base))
    nasty = base.copy()
    nasty[1] = np.nan
    nasty[2] = np.inf
    nasty[3] = -np.inf
    nasty[4] = np.float32(-0.0)
    nasty[5] = np.float32(1e-42)  # subnormal
    out.append(("xdelta", nasty, base))
    out.append(("nm", np.empty(0, np.float32), np.empty(0, np.float32)))
    # single-element change: an 8-byte xdelta can never beat 4 raw bytes
    one = np.array([np.float32(-0.0)], np.float32)
    out.append(("full", np.array([np.float32(0.0)], np.float32), one))
    odd = rng.standard_normal(7).astype(np.float32)
    out.append(("full", rng.standard_normal(7).astype(np.float32), odd))
    return out


@needs_native
class TestWireDeltaIdentity:
    def test_crc_bit_identity(self, cf):
        for _, cur, _ in _vectors():
            py, nat = both(cf, lambda c=cur: wiredelta.crc(c))
            assert py == nat == (zlib.crc32(cur.tobytes()) & 0xFFFFFFFF)

    def test_encode_bit_identity(self, cf):
        for want, cur, basis in _vectors():
            py, nat = both(cf, lambda c=cur, b=basis: wiredelta.encode(c, b))
            assert py == nat, (want, py[0], nat[0])
            assert py[0] == want

    def test_encode_xfull_bit_identity(self, cf):
        for _, cur, basis in _vectors():
            py, nat = both(
                cf, lambda c=cur, b=basis: wiredelta.encode_xfull(c, b))
            assert py == nat

    def test_cross_backend_decode(self, cf):
        """python-encoded deltas decode natively and vice versa -- the
        wire never knows which side ran which implementation."""
        for _, cur, basis in _vectors():
            want_crc = wiredelta.crc(cur)
            for enc_native in (False, True):
                cf.set("async.native.enabled", enc_native)
                wenc, payload, nnz = wiredelta.encode(cur, basis)
                cf.set("async.native.enabled", not enc_native)
                out = wiredelta.decode(wenc, payload, nnz, basis, want_crc,
                                       basis_crc=wiredelta.crc(basis))
                assert out is not None
                assert out.tobytes() == cur.tobytes()

    def test_xfull_decode_cross_backend(self, cf):
        for _, cur, basis in _vectors():
            if cur.size == 0:
                continue
            want_crc = wiredelta.crc(cur)
            for enc_native in (False, True):
                cf.set("async.native.enabled", enc_native)
                payload = wiredelta.encode_xfull(cur, basis)
                cf.set("async.native.enabled", not enc_native)
                out = wiredelta.decode(wiredelta.XFULL, payload, 0,
                                       basis, want_crc)
                assert out is not None and out.tobytes() == cur.tobytes()


# --------------------------------------------------------- grad codecs
def _grads():
    rng = np.random.default_rng(7)
    g = rng.standard_normal(777).astype(np.float32)
    g[3] = np.float32(-0.0)
    g[4] = np.float32(1e-42)
    err = (rng.standard_normal(777).astype(np.float32)
           * np.float32(1e-3))
    return g, err


@needs_native
class TestWireCodecIdentity:
    @pytest.mark.parametrize("codec", [wirecodec.FP16, wirecodec.INT8])
    @pytest.mark.parametrize("with_err", [False, True])
    def test_encode_grad_bit_identity(self, cf, codec, with_err):
        g, err = _grads()
        py, nat = both(cf, lambda: wirecodec.encode_grad(
            g, codec, err.copy() if with_err else None))
        assert (py is None) == (nat is None)
        assert py[0] == nat[0]              # header incl. int8 scale
        assert py[1] == nat[1]              # quantized payload bytes
        assert py[2].tobytes() == nat[2].tobytes()  # residual, bitwise

    @pytest.mark.parametrize("codec", [wirecodec.FP16, wirecodec.INT8])
    def test_nonfinite_refuses_both_backends(self, cf, codec):
        g, err = _grads()
        for bad in (np.nan, np.inf, -np.inf):
            g2 = g.copy()
            g2[11] = np.float32(bad)
            py, nat = both(cf, lambda x=g2: wirecodec.encode_grad(
                x, codec, err.copy()))
            assert py is None and nat is None

    def test_fp16_overflow_refuses_both_backends(self, cf):
        g, _ = _grads()
        g2 = g.copy()
        g2[0] = np.float32(1e5)
        py, nat = both(cf, lambda: wirecodec.encode_grad(
            g2, wirecodec.FP16, None))
        assert py is None and nat is None
        # int8 has no overflow refusal: both encode, identically
        py, nat = both(cf, lambda: wirecodec.encode_grad(
            g2, wirecodec.INT8, None))
        assert py[1] == nat[1] and py[0] == nat[0]

    @pytest.mark.parametrize("codec", [wirecodec.FP16, wirecodec.INT8])
    def test_decode_grad_cross_backend(self, cf, codec):
        g, err = _grads()
        cf.set("async.native.enabled", False)
        hdr, payload, _ = wirecodec.encode_grad(g, codec, err.copy())
        py, nat = both(cf, lambda: wirecodec.decode_grad(
            hdr, payload, g.size))
        assert py.tobytes() == nat.tobytes()

    def test_transform_bit_identity(self, cf):
        rng = np.random.default_rng(3)
        for n in (0, 4, 4096):
            payload = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            py, nat = both(cf, lambda p=payload: wirecodec._shuffle4(p))
            assert py == nat
            py, nat = both(cf, lambda p=payload: wirecodec._unshuffle4(p))
            assert py == nat
            assert wirecodec._unshuffle4(wirecodec._shuffle4(payload)) \
                == payload
        for m in (0, 1, 513):
            idx = np.sort(rng.choice(1 << 20, m, replace=False)
                          ).astype(np.uint32)
            py, nat = both(cf, lambda i=idx: wirecodec._delta_idx(i))
            assert py.tobytes() == nat.tobytes()
            py, nat = both(cf, lambda d=py: wirecodec._cumsum_idx(d))
            assert py.tobytes() == nat.tobytes()
            assert py.tobytes() == idx.tobytes()

    def test_compress_model_part_identical_wire(self, cf):
        """Compression output (transform + deflate) is byte-identical
        across backends: flipping the knob never changes the wire."""
        rng = np.random.default_rng(5)
        basis = rng.standard_normal(4096).astype(np.float32)
        cur = basis.copy()
        cur[rng.choice(4096, 200, replace=False)] += np.float32(1e-3)
        wenc, payload, nnz = wiredelta.encode(cur, basis)
        assert wenc == wiredelta.XDELTA
        py, nat = both(cf, lambda: wirecodec.compress_model_part(
            wenc, payload, nnz))
        assert py[0] == nat[0] and py[1] == nat[1]
        hdr, wire = py
        rt_py, rt_nat = both(cf, lambda: wirecodec.decompress_model_part(
            {**hdr, "nnz": nnz}, wire))
        assert rt_py == rt_nat == payload


@needs_native
class TestFrameGather:
    def test_gather_bit_identity(self, cf):
        rng = np.random.default_rng(11)
        parts = [
            rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            for n in (0, 1, 63, 4096)
        ]
        cases = [parts, [b""], [], [memoryview(parts[3]),
                                    bytearray(parts[2]), parts[1]]]
        for case in cases:
            py, nat = both(cf, lambda c=case: frame.gather(c))
            assert py == nat == b"".join(bytes(p) for p in case)


# ------------------------------------------------------ ring transport
@needs_native
class TestRingCrossBackend:
    @pytest.mark.parametrize("w_native", [False, True])
    @pytest.mark.parametrize("r_native", [False, True])
    def test_stream_and_eof(self, cf, w_native, r_native):
        """Every backend combination streams the same bytes through the
        same segment (incl. wraparound) and agrees on the EOF flag."""
        cf.set("async.native.enabled", w_native)
        wr = ShmRing.create(4096)
        cf.set("async.native.enabled", r_native)
        rd = ShmRing.attach(wr.path)
        try:
            data = np.random.default_rng(13).integers(
                0, 256, 3 * 4096 + 123, dtype=np.uint8).tobytes()
            got = bytearray()
            buf = bytearray(1024)
            off = 0
            while off < len(data) or len(got) < len(data):
                if off < len(data):
                    w = wr.write(memoryview(data)[off:off + 1024])
                    assert w >= 0
                    off += w
                r = rd.read_into(memoryview(buf))
                assert r >= 0
                got += buf[:r]
            assert bytes(got) == data
            wr.latch_closed(as_writer=True)
            assert rd.read_into(memoryview(buf)) == -1  # clean EOF
        finally:
            rd.close()
            wr.close()
            os.unlink(wr.path)


class TestShmSocketIntegration:
    @pytest.mark.parametrize("use_native", [False, True])
    def test_upgrade_and_roundtrip(self, cf, use_native):
        """A real SHM_OPEN handshake over loopback TCP: frames round-trip
        through the rings, the segments are unlinked before the first
        data frame, and both sides count the upgrade."""
        if use_native and not NATIVE_OK:
            pytest.skip("no C++ toolchain (wire natives not built)")
        cf.set("async.shm.enabled", True)
        cf.set("async.native.enabled", use_native)
        base = dict(native_totals())
        srv_err = []
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(1)
        port = lsock.getsockname()[1]

        def serve():
            try:
                conn, _ = lsock.accept()
                conn.settimeout(15)
                header, _ = frame.recv_msg(conn)
                assert header.get("op") == "SHM_OPEN"
                sh = shmring.serve_attach(conn, header)
                assert sh is not None
                h, payload = frame.recv_msg(sh)
                frame.send_msg(sh, {"op": "PONG", "tag": h["tag"]},
                               payload[::-1])
                sh.close()
            except Exception as e:  # pragma: no cover - surfaced below
                srv_err.append(e)

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        sock = socket.create_connection(("127.0.0.1", port), timeout=15)
        sock.settimeout(15)
        tr, upgraded = shmring.maybe_upgrade(sock)
        assert upgraded and isinstance(tr, ShmSocket)
        # segment names are already unlinked: kill -9 can't leak them
        assert not os.path.exists(tr._rd.path)
        assert not os.path.exists(tr._wr.path)
        payload = os.urandom(65536 + 17)  # bigger than one ring pass
        frame.send_msg(tr, {"op": "PING", "tag": 42}, payload)
        h, back = frame.recv_msg(tr)
        assert h["op"] == "PONG" and h["tag"] == 42
        assert back == payload[::-1]
        tr.close()
        t.join(timeout=15)
        lsock.close()
        assert not srv_err, srv_err
        totals = native_totals()
        assert totals.get("shm_upgrades", 0) - base.get("shm_upgrades", 0) \
            == 2  # client + server, same process
        assert totals.get("shm_frames_sent", 0) \
            > base.get("shm_frames_sent", 0)

    def test_conf_off_refuses(self, cf):
        cf.set("async.shm.enabled", False)
        a, b = socket.socketpair()
        try:
            tr, upgraded = shmring.maybe_upgrade(a)
            assert tr is a and not upgraded
        finally:
            a.close()
            b.close()


_KILL_CHILD = """\
import sys
import time

sys.path.insert(0, {repo!r})
from asyncframework_tpu.net.shmring import ShmRing

ring = ShmRing.attach(sys.argv[1])
ring.stamp_pid(as_writer=True)
mv = memoryview(b"HELLOSHM")
off = 0
while off < len(mv):
    w = ring.write(mv[off:])
    if w > 0:
        off += w
time.sleep(120)
"""


@needs_native
@pytest.mark.chaos
class TestShmKillChaos:
    def test_sigkill_peer_degrades_not_hangs(self, cf, tmp_path):
        """kill -9 of the ring peer mid-stream: the survivor's next read
        raises ConnectionError within the liveness window (never waits
        out the full timeout) and the degrade is counted."""
        cf.set("async.native.enabled", True)
        base = dict(native_totals())
        rd = ShmRing.create(65536)
        wr = ShmRing.create(65536)
        rd.stamp_pid(as_writer=False)
        script = tmp_path / "shm_kill_child.py"
        script.write_text(_KILL_CHILD.format(repo=REPO))
        env = dict(os.environ, PYTHONPATH=REPO)
        child = subprocess.Popen([sys.executable, str(script), rd.path],
                                 env=env)
        a, b = socket.socketpair()
        sock = ShmSocket(rd=rd, wr=wr, tcp=a)
        sock.settimeout(30)
        try:
            buf = bytearray(8)
            got = 0
            while got < 8:
                got += sock.recv_into(memoryview(buf)[got:])
            assert bytes(buf) == b"HELLOSHM"
            child.send_signal(signal.SIGKILL)
            child.wait(timeout=15)
            t0 = time.monotonic()
            with pytest.raises(ConnectionError):
                sock.recv_into(buf)
            assert time.monotonic() - t0 < 10  # liveness, not timeout
            assert native_totals().get("shm_degrades", 0) \
                > base.get("shm_degrades", 0)
        finally:
            if child.poll() is None:  # pragma: no cover - assert failed
                child.kill()
                child.wait()
            sock.close()
            b.close()
            for ring in (rd, wr):
                try:
                    os.unlink(ring.path)
                except OSError:
                    pass


# --------------------------------------------------- toolchain-absent
class TestToolchainAbsent:
    def test_check_status_reports_no_toolchain(self, tmp_path, monkeypatch):
        src = os.path.join(native_build.native_dir(), "wiredelta.cc")
        if not os.path.exists(src):
            pytest.skip("source tree ships no native/*.cc")
        with open(src, "rb") as f:
            (tmp_path / "wiredelta.cc").write_bytes(f.read())
        monkeypatch.setattr(native_build, "_NATIVE_DIR", str(tmp_path))
        monkeypatch.setenv("CXX", "/definitely/not/a/compiler")
        assert native_build.check_status("wiredelta") \
            == "missing, no-toolchain"
        assert native_build.ensure_built("wiredelta") is None

    def test_wanted_but_unavailable_degrades_visibly(self, cf, monkeypatch):
        """native on + no library: correct answers from the oracle AND a
        python_fallbacks bump -- the silent degrade is never silent."""
        monkeypatch.setattr(wiredelta, "_NATIVE", False)
        cf.set("async.native.enabled", True)
        base = native_totals().get("python_fallbacks", 0)
        buf = np.arange(16, dtype=np.float32)
        assert wiredelta.crc(buf) \
            == (zlib.crc32(buf.tobytes()) & 0xFFFFFFFF)
        assert native_totals().get("python_fallbacks", 0) > base


# ------------------------------------------------- native-oracle lint
GOOD_DISPATCH = '''
import ctypes
from asyncframework_tpu.native_build import ensure_built

NATIVE_ORACLES = {"fx_add": "_py_add"}
_LIB = None


def _native_lib():
    global _LIB
    if _LIB is None:
        path = ensure_built("fx")
        _LIB = ctypes.CDLL(path)
        _LIB.fx_add.restype = ctypes.c_int
    return _LIB


def _py_add(a, b):
    return a + b


def add(a, b):
    lib = _native_lib()
    if lib is not None:
        return lib.fx_add(a, b)
    return _py_add(a, b)
'''


@pytest.mark.lint
class TestNativeOracleRule:
    def _findings(self, tmp_path, src):
        rel = "asyncframework_tpu/net/fx.py"
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        ctx = LintContext(str(tmp_path), paths=[rel])
        return rules_native.check(ctx)

    def test_good_module_is_clean(self, tmp_path):
        assert self._findings(tmp_path, GOOD_DISPATCH) == []

    def test_deleted_entry_fires_missing(self, tmp_path):
        src = GOOD_DISPATCH.replace(
            'NATIVE_ORACLES = {"fx_add": "_py_add"}', "NATIVE_ORACLES = {}")
        f = self._findings(tmp_path, src)
        assert [x.rule for x in f] == ["native-oracle-missing"]
        assert f[0].token == "fx_add"

    def test_deleted_table_fires_missing(self, tmp_path):
        src = GOOD_DISPATCH.replace(
            'NATIVE_ORACLES = {"fx_add": "_py_add"}\n', "")
        f = self._findings(tmp_path, src)
        assert [x.rule for x in f] == ["native-oracle-missing"]

    def test_deleted_fallback_fires(self, tmp_path):
        src = GOOD_DISPATCH.replace(
            "    return _py_add(a, b)\n", "    return 0\n")
        f = self._findings(tmp_path, src)
        assert [x.rule for x in f] == ["native-fallback-missing"]
        assert f[0].token == "fx_add"

    def test_renamed_oracle_fires_undefined(self, tmp_path):
        src = GOOD_DISPATCH.replace("def _py_add", "def _py_sum")
        rules = {x.rule for x in self._findings(tmp_path, src)}
        assert "native-oracle-undefined" in rules

    def test_stale_entry_fires(self, tmp_path):
        src = GOOD_DISPATCH.replace(
            '{"fx_add": "_py_add"}',
            '{"fx_add": "_py_add", "fx_gone": "_py_add"}')
        f = self._findings(tmp_path, src)
        assert [x.rule for x in f] == ["native-oracle-stale"]
        assert f[0].token == "fx_gone"

    def test_class_shaped_twin_needs_instantiation(self, tmp_path):
        src = GOOD_DISPATCH.replace(
            '{"fx_add": "_py_add"}', '{"fx_add": "_Py.add"}') + (
            "\n\nclass _Py:\n    def add(self, a, b):\n        return a + b\n")
        rules = [x.rule for x in self._findings(tmp_path, src)]
        assert rules == ["native-fallback-missing"]
        fixed = src + "\n_INSTANCE = _Py()\n"
        assert self._findings(tmp_path, fixed) == []

    def test_real_tree_is_clean(self):
        result = run_lint(REPO, rules=["native"])
        assert result.findings == [], [f.format() for f in result.findings]
