"""AsyncContext / WorkerState / PartialResult unit tests (pure logic).

Covers the semantics of the reference's ASYNCcontext/workerState/RDDPartialRes
(queue, logical clock, staleness bookkeeping, availability aggregates).
"""

import threading
import time

import pytest

from asyncframework_tpu.context import AsyncContext, PartialResult, WorkerState


def test_partial_result_fields():
    r = PartialResult(data=[1, 2], staleness=3, batch_size=10, worker_id=7)
    assert r.get_task_result() == [1, 2]
    assert r.get_staleness() == 3
    assert r.get_batch_size() == 10
    assert r.get_worker_id() == 7


def test_clock_semantics():
    ac = AsyncContext()
    assert ac.get_current_time() == 0
    ac.add_to_current_time(1)
    ac.add_to_current_time(2)
    assert ac.get_current_time() == 3
    ac.set_current_time(10)
    assert ac.get_current_time() == 10
    ac.set_last_time(10)
    assert ac.is_old()
    ac.add_to_current_time(1)
    assert not ac.is_old()


def test_queue_collect_order_and_size():
    ac = AsyncContext()
    for i in range(5):
        ac.put(PartialResult(i, 0, 1, i))
    assert ac.size() == 5
    assert ac.has_next()
    assert ac.collect() == 0
    got = ac.collect_all()
    assert got.data == 1 and got.worker_id == 1
    rest = [r.data for r in ac.drain()]
    assert rest == [2, 3, 4]
    assert not ac.has_next()


def test_merge_result_staleness_and_clock():
    ac = AsyncContext()
    ac.mark_busy([0, 1])
    ts = ac.get_current_time()  # 0
    # worker 0 finishes first: staleness 0, clock -> 1
    r0 = ac.merge_result(0, "g0", submit_clock=ts, elapsed_ms=10.0, batch_size=4)
    assert r0.staleness == 0
    assert ac.get_current_time() == 1
    # worker 1 finishes after one other gradient arrived: staleness 1
    r1 = ac.merge_result(1, "g1", submit_clock=ts, elapsed_ms=30.0, batch_size=4)
    assert r1.staleness == 1
    assert ac.get_current_time() == 2
    s0, s1 = ac.get_state(0), ac.get_state(1)
    assert s0.available and s1.available
    assert s0.num_tasks == 1
    assert s0.average_task_time == pytest.approx(10.0)
    # second task for worker 0: running mean of task latencies
    ac.mark_busy([0])
    assert not ac.get_state(0).available
    ac.merge_result(0, "g0b", submit_clock=2, elapsed_ms=30.0, batch_size=4)
    assert ac.get_state(0).num_tasks == 2
    assert ac.get_state(0).average_task_time == pytest.approx(20.0)


def test_availability_aggregates():
    ac = AsyncContext()
    assert ac.max_staleness() == -1  # reference returns -1 on empty table
    ac.mark_busy([0, 1, 2, 3])
    assert ac.available_workers() == 0
    ac.merge_result(1, None, 0, 1.0, 1)
    ac.mark_available(3)
    assert ac.available_workers() == 2
    ws = ac.get_state(1)
    assert ws.get_available_workers() == 2  # delegate API parity
    ac.merge_result(0, None, 0, 1.0, 1)  # staleness = clock(1) - 0 = 1
    assert ac.max_staleness() == 1
    assert ws.get_max_staleness() == 1


def test_mark_available_does_not_bump_clock():
    ac = AsyncContext()
    ac.mark_busy([0])
    ac.mark_available(0)  # empty-result path
    assert ac.get_current_time() == 0
    assert ac.available_workers() == 1


def test_concurrent_producers_single_consumer():
    """Producer/consumer stress: N producers stream, one consumer drains."""
    ac = AsyncContext()
    n_workers, per = 8, 50

    def produce(wid):
        for i in range(per):
            ac.merge_result(wid, (wid, i), submit_clock=0, elapsed_ms=1.0, batch_size=1)

    threads = [threading.Thread(target=produce, args=(w,)) for w in range(n_workers)]
    for t in threads:
        t.start()
    seen = 0
    deadline = time.time() + 10
    while seen < n_workers * per and time.time() < deadline:
        ac.collect_all(timeout=5)
        seen += 1
    for t in threads:
        t.join()
    assert seen == n_workers * per
    assert ac.get_current_time() == n_workers * per
    assert ac.available_workers() == n_workers
