"""Relaycast distribution plane (ISSUE 12): peer-relayed versioned model
distribution.

The correctness spine:

- the tree is a pure function of (replica count, fanout): every node
  computes the same parent with zero coordination, child sets partition
  the replicas, depth is logarithmic;
- a relayed model is ALWAYS a version the PS actually published: every
  hop re-validates the version CRC (full peer payloads included -- a
  peer is never authoritative), and any mismatch re-homes the child to
  the root (direct SUBSCRIBE, the existing safe path);
- epoch fencing gates every hop: a stale-epoch fetch is REJECT_FENCED,
  and a parent serving versions from a superseded epoch is refused
  client-side -- a deposed peer can never poison the subtree;
- PS egress is O(fanout): with the tree on, subscribe bytes at the PS
  grow with the root's child count, not the replica count (the direct-
  SUBSCRIBE control is the N x baseline);
- a SIGKILLed interior node degrades to root traffic for its subtree,
  never to staleness or torn models (the chaos acceptance, seeded, on
  REAL OS processes -- rides every bin/chaos_sweep.py seed).
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.conf import set_global_conf
from asyncframework_tpu.metrics import reset_totals
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net import faults, wiredelta
from asyncframework_tpu.net.retry import reset_breakers
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.relaycast import (
    ROOT,
    RelayNode,
    RelaySource,
    children_of,
    depth_of,
    parent_index,
)
from asyncframework_tpu.relaycast import metrics as rmetrics
from asyncframework_tpu.serving.replica import ModelReplica
from asyncframework_tpu.solvers import SolverConfig

pytestmark = pytest.mark.relay

REPO = Path(__file__).parent.parent
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


def make_cfg(**kw):
    defaults = dict(
        num_workers=2, num_iterations=10_000, gamma=0.5, taw=2 ** 31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=100, seed=42,
        calibration_iters=4, run_timeout_s=60.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    reset_totals()
    reset_breakers()
    faults.clear()
    yield
    reset_totals()
    reset_breakers()
    faults.clear()
    set_global_conf(None)


def start_ps(devices, cfg=None, d=64, n=256):
    cfg = cfg or make_cfg()
    ps = ps_dcn.ParameterServer(cfg, d, n, device=devices[0],
                                port=0).start()
    return ps, d


def push_once(cl, wid, d, g=None, scale=0.05, seed_rng=None):
    ts, _w, _avg, _cal = cl.pull(wid)
    if g is None:
        rng = seed_rng or np.random.default_rng(0)
        g = (scale * rng.normal(size=d)).astype(np.float32)
    cl.push(wid, ts, np.asarray(g, np.float32))


def fetch_raw(port, have=None, ep=None, rport=None):
    """One raw RELAY_FETCH frame against a node."""
    hdr = {"op": "RELAY_FETCH", "rid": 99}
    if have is not None:
        hdr["have"] = have
    if ep is not None:
        hdr["ep"] = ep
    if rport is not None:
        hdr["rport"] = rport
    sock = _frame.connect(("127.0.0.1", port))
    try:
        _frame.send_msg(sock, hdr)
        return _frame.recv_msg(sock)
    finally:
        sock.close()


# ------------------------------------------------------------------ the plan
class TestTreePlan:
    @pytest.mark.parametrize("n,k", [(1, 1), (2, 2), (8, 2), (9, 2),
                                     (27, 3), (100, 4), (5, 8)])
    def test_plan_is_a_partitioned_forest(self, n, k):
        roots = [i for i in range(n) if parent_index(i, k) == ROOT]
        assert roots == list(range(min(k, n)))
        seen = set(roots)
        for i in range(n):
            kids = children_of(i, n, k)
            assert len(kids) <= k
            for c in kids:
                assert parent_index(c, k) == i
                assert c not in seen  # each node has ONE parent
                seen.add(c)
        assert seen == set(range(n))  # every replica is in the forest

    @pytest.mark.parametrize("n,k", [(64, 2), (64, 4), (1000, 4)])
    def test_depth_is_logarithmic(self, n, k):
        import math

        max_depth = max(depth_of(i, k) for i in range(n))
        assert max_depth <= math.ceil(math.log(n + 1, k)) + 1

    def test_bad_inputs(self):
        with pytest.raises(ValueError):
            parent_index(-1, 2)
        with pytest.raises(ValueError):
            parent_index(3, 0)


# ------------------------------------------------------------------ the node
def _publish(node, w, ts, crc=None, epoch=0, clock=None, done=False):
    wire = np.asarray(w, np.float32).tobytes()
    node.publish(ts, wire, crc if crc is not None else wiredelta.crc(wire),
                 clock if clock is not None else ts, ts, 0.0, done,
                 epoch=epoch)


class TestRelayNode:
    def test_empty_node_answers_err(self):
        node = RelayNode(rid=0, port=0, compress=False).start()
        try:
            hdr, _ = fetch_raw(node.port)
            assert hdr["op"] == "ERR"
        finally:
            node.stop()

    def test_fetch_shapes_full_then_nm_then_delta(self, rng):
        node = RelayNode(rid=0, port=0, compress=False).start()
        try:
            w1 = rng.normal(size=64).astype(np.float32)
            _publish(node, w1, ts=1)
            hdr, payload = fetch_raw(node.port)
            assert hdr["op"] == "RELAY_MODEL" and hdr["wenc"] == "full"
            got = wiredelta.decode("full", payload, 0, None, None)
            assert got.tobytes() == w1.tobytes()
            assert wiredelta.crc(got) == hdr["crc"]
            # same version + have -> header-only NOT_MODIFIED
            hdr, payload = fetch_raw(node.port, have=1)
            assert hdr["wenc"] == "nm" and payload == b""
            # sparse change -> xdelta against the stored basis
            w2 = w1.copy()
            w2[5] += 0.25
            _publish(node, w2, ts=2)
            hdr, payload = fetch_raw(node.port, have=1)
            assert hdr["wenc"] == "xdelta" and hdr["nnz"] == 1
            got = wiredelta.decode("xdelta", payload, 1, w1, hdr["crc"])
            assert got is not None and got.tobytes() == w2.tobytes()
        finally:
            node.stop()

    def test_dense_change_ships_xfull_and_compresses(self, rng):
        from asyncframework_tpu.net import wirecodec

        node = RelayNode(rid=0, port=0, compress=True).start()
        try:
            w1 = rng.normal(size=1024).astype(np.float32)
            w2 = (w1 * (1 + 1e-4 * rng.normal(size=1024))).astype(
                np.float32)
            _publish(node, w1, ts=1)
            _publish(node, w2, ts=2)
            hdr, payload = fetch_raw(node.port, have=1)
            assert hdr["wenc"] == "xfull"
            assert hdr.get("cz") == "zs"
            assert len(payload) * 2 <= w1.nbytes  # the >= 2x cut
            raw = wirecodec.decompress_model_part(hdr, payload)
            got = wiredelta.decode("xfull", raw, 0, w1, hdr["crc"])
            assert got is not None and got.tobytes() == w2.tobytes()
        finally:
            node.stop()

    def test_publish_is_monotone(self, rng):
        node = RelayNode(rid=0, port=0, compress=False)
        w1, w2 = (rng.normal(size=8).astype(np.float32) for _ in range(2))
        _publish(node, w2, ts=5)
        _publish(node, w1, ts=3)  # late straggler must not roll back
        assert node.current().ts == 5

    def test_store_evicts_oldest(self, rng):
        node = RelayNode(rid=0, port=0, versions=2, compress=False)
        for ts in (1, 2, 3):
            _publish(node, rng.normal(size=8).astype(np.float32), ts=ts)
        assert node.basis_for(1) is None
        assert node.basis_for(3) is not None

    def test_fence_admission_on_fetch_and_offer(self, rng):
        node = RelayNode(rid=0, port=0, compress=False).start()
        try:
            _publish(node, rng.normal(size=8).astype(np.float32), ts=1,
                     epoch=2)
            assert node.epoch == 2
            # stale-epoch fetch -> REJECT_FENCED with the newest epoch
            hdr, _ = fetch_raw(node.port, ep=1)
            assert hdr["op"] == "REJECT_FENCED" and hdr["epoch"] == 2
            assert rmetrics.relay_totals().get("fenced_hops", 0) == 1
            # current epoch serves; newer epoch advances our belief
            hdr, _ = fetch_raw(node.port, ep=2)
            assert hdr["op"] == "RELAY_MODEL"
            hdr, _ = fetch_raw(node.port, ep=3)
            assert hdr["op"] == "RELAY_MODEL"
            assert node.epoch == 3
            # unstamped op (fencing-off client) is always served
            hdr, _ = fetch_raw(node.port)
            assert hdr["op"] == "RELAY_MODEL"
        finally:
            node.stop()

    def test_children_learned_from_fetch_and_offered(self, rng):
        parent = RelayNode(rid=0, port=0, compress=False,
                           fanout=2).start()
        offers = []
        child = RelayNode(rid=1, port=0, compress=False,
                          on_offer=lambda: offers.append(1)).start()
        try:
            _publish(parent, rng.normal(size=8).astype(np.float32), ts=1)
            fetch_raw(parent.port, rport=child.port)
            assert ("127.0.0.1", child.port) in parent.children()
            # fanout-bounded LRU: two newer registrants displace the
            # oldest entries; a later fetch from the real child renews
            # its slot (registration IS the renewal), displacing one of
            # them in turn -- a registrant that stopped fetching can
            # never squat a slot a live child keeps renewing
            fetch_raw(parent.port, rport=65000)
            fetch_raw(parent.port, rport=65001)
            assert len(parent.children()) == 2
            assert ("127.0.0.1", child.port) not in parent.children()
            fetch_raw(parent.port, rport=child.port)
            assert ("127.0.0.1", child.port) in parent.children()
            _publish(parent, rng.normal(size=8).astype(np.float32), ts=2)
            delivered = parent.offer_children()
            assert delivered == 1  # the real child; the fake one strikes
            assert offers == [1]
            assert child.offered_ts == 2
        finally:
            parent.stop()
            child.stop()

    def test_stale_parent_reply_never_rolls_served_model_back(
            self, devices8, rng):
        """Review fix: monotone RETURN, not just monotone store.  A
        child that re-homed to the root and serves v2 polls a parent
        still holding v1; the parent's (CRC-valid!) v1 FULL reply must
        not be handed to the replica -- the source answers v2 from its
        own store."""
        ps, d = start_ps(devices8)
        parent = RelayNode(rid=0, port=0).start()
        node = RelayNode(rid=1, port=0)
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            push_once(cl, 0, d)
            # parent validates and stores v1
            RelaySource("127.0.0.1", ps.port, parent).subscribe(0)
            # the child, currently re-homed, gets v2 from the root
            src = RelaySource("127.0.0.1", ps.port, node,
                              parent=("127.0.0.1", parent.port), rid=1,
                              retry_parent_s=0.0)
            push_once(cl, 0, d)
            src._parent_dark_until = time.monotonic() + 60
            got2 = src.subscribe(1)
            assert got2[0] == 2
            # cooloff expires; the parent (still at v1) answers the next
            # poll -- subscribe must return v2's bytes, not v1's
            src._parent_dark_until = 0.0
            got3 = src.subscribe(1)
            assert got3[0] == 2
            assert got3[1].tobytes() == got2[1].tobytes()
            assert rmetrics.relay_totals().get("stale_replies", 0) == 1
        finally:
            parent.stop()
            node.stop()
            ps.stop()


# ---------------------------------------------------------------- the source
class TestRelaySource:
    def test_parent_chain_is_byte_exact(self, devices8, rng):
        """root-child and grandchild sources deliver the PS's bytes
        identically through the relay hop."""
        ps, d = start_ps(devices8)
        n0 = RelayNode(rid=0, port=0).start()
        n1 = RelayNode(rid=1, port=0).start()
        try:
            s0 = RelaySource("127.0.0.1", ps.port, n0)
            s1 = RelaySource("127.0.0.1", ps.port, n1,
                             parent=("127.0.0.1", n0.port), rid=1)
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            seed_rng = np.random.default_rng(1)
            for v in range(1, 6):
                push_once(cl, 0, d, seed_rng=seed_rng)
                got0 = s0.subscribe(0)
                got1 = s1.subscribe(1)
                assert got0[0] == got1[0] == v
                assert got0[1].tobytes() == got1[1].tobytes()
            assert s1.via_parent >= 4  # boot round may fall to root
            assert s1.pull_wenc["full"] + s1.pull_wenc.get("xfull", 0) \
                + s1.pull_wenc["xdelta"] + s1.pull_wenc["nm"] >= 5
        finally:
            n0.stop()
            n1.stop()
            ps.stop()

    def test_dead_parent_rehomes_to_root_with_cooloff(self, devices8,
                                                      rng):
        ps, d = start_ps(devices8)
        node = RelayNode(rid=1, port=0)
        try:
            # parent endpoint nobody listens on
            src = RelaySource("127.0.0.1", ps.port, node,
                              parent=("127.0.0.1", 1), rid=1,
                              retry_parent_s=30.0)
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            push_once(cl, 0, d)
            got = src.subscribe(1)
            assert got is not None and got[0] == 1
            assert rmetrics.relay_totals().get("rehomes", 0) == 1
            assert src.via_root == 1
            # cooloff: the next round goes straight to root, no re-dial
            push_once(cl, 0, d)
            got = src.subscribe(1)
            assert got[0] == 2
            assert rmetrics.relay_totals().get("rehomes", 0) == 1
        finally:
            node.stop()
            ps.stop()

    def test_empty_parent_falls_back_without_cooloff(self, devices8,
                                                     rng):
        ps, d = start_ps(devices8)
        parent = RelayNode(rid=0, port=0).start()  # alive, no model
        node = RelayNode(rid=1, port=0)
        try:
            src = RelaySource("127.0.0.1", ps.port, node,
                              parent=("127.0.0.1", parent.port), rid=1,
                              retry_parent_s=30.0)
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            push_once(cl, 0, d)
            got = src.subscribe(1)
            assert got[0] == 1 and src.via_root == 1
            assert rmetrics.relay_totals().get("rehomes", 0) == 0
            # parent catches up; the NEXT round uses it (no cooloff)
            _publish(parent, got[1], ts=1)
            push_once(cl, 0, d)
            _publish(parent,
                     RelaySource("127.0.0.1", ps.port,
                                 RelayNode(rid=9, port=0)
                                 ).subscribe(9)[1], ts=2)
            got = src.subscribe(1)
            assert got[0] == 2 and src.via_parent == 1
        finally:
            parent.stop()
            node.stop()
            ps.stop()

    def test_corrupt_parent_bytes_rehome_never_serve(self, devices8,
                                                     rng):
        """A parent whose stored bytes rot serves nothing: CRC refuses
        both the delta and the full refetch, the child re-homes."""
        ps, d = start_ps(devices8)
        parent = RelayNode(rid=0, port=0).start()
        node = RelayNode(rid=1, port=0)
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            push_once(cl, 0, d)
            psrc = RelaySource("127.0.0.1", ps.port, parent)
            psrc.subscribe(0)
            # rot the stored wire bytes behind the recorded CRC
            cur = parent.current()
            bad = bytearray(cur.wire)
            bad[0] ^= 0xFF
            cur.wire = bytes(bad)
            src = RelaySource("127.0.0.1", ps.port, node,
                              parent=("127.0.0.1", parent.port), rid=1,
                              retry_parent_s=30.0)
            got = src.subscribe(1)
            assert got[0] == 1
            # the served model came from the ROOT and is byte-correct
            snap = ps._model_snap()
            assert got[1].tobytes() == snap.w_host.tobytes()
            assert rmetrics.relay_totals().get("crc_rejects", 0) >= 1
            assert rmetrics.relay_totals().get("rehomes", 0) == 1
        finally:
            parent.stop()
            node.stop()
            ps.stop()

    def test_stale_epoch_parent_is_refused(self, devices8, rng):
        """A parent holding versions from a superseded epoch cannot
        feed a child that already knows the newer epoch."""
        ps, d = start_ps(devices8)
        parent = RelayNode(rid=0, port=0).start()
        node = RelayNode(rid=1, port=0)
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            push_once(cl, 0, d)
            psrc = RelaySource("127.0.0.1", ps.port, parent)
            got = psrc.subscribe(0)
            # the parent's stored version carries epoch 1; the child
            # believes epoch 2 (a failover happened upstream)
            cur = parent.current()
            cur.vep = 1
            parent.epoch = 0  # parent never saw fencing: serves anyway
            node.epoch = 2
            src = RelaySource("127.0.0.1", ps.port, node,
                              parent=("127.0.0.1", parent.port), rid=1,
                              retry_parent_s=30.0)
            got2 = src.subscribe(1)
            assert got2[0] == 1  # served -- by the root, not the parent
            assert src.via_root == 1 and src.via_parent == 0
            assert rmetrics.relay_totals().get(
                "stale_epoch_rejects", 0) == 1
        finally:
            parent.stop()
            node.stop()
            ps.stop()

    def test_stale_vep_reject_skips_futile_full_refetch(self, devices8,
                                                        rng):
        """Review fix: a header-level stale-vep reject must NOT trigger
        the full refetch (the same parent rejects the full identically)
        -- only payload decode failures earn it."""
        ps, d = start_ps(devices8)
        parent = RelayNode(rid=0, port=0).start()
        node = RelayNode(rid=1, port=0)
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            push_once(cl, 0, d)
            RelaySource("127.0.0.1", ps.port, parent).subscribe(0)
            src = RelaySource("127.0.0.1", ps.port, node,
                              parent=("127.0.0.1", parent.port), rid=1,
                              retry_parent_s=30.0)
            got = src.subscribe(1)  # healthy round: node gains a basis
            assert got[0] == 1 and src.via_parent == 1
            # the parent's stored version goes epoch-stale
            parent.current().vep = 1
            node.epoch = 2
            push_once(cl, 0, d)
            fetches_before = parent.fetches
            got = src.subscribe(1)  # re-homes to root
            assert got[0] == 2 and src.via_root == 1
            # exactly ONE fetch hit the parent (no full refetch)
            assert parent.fetches == fetches_before + 1
            assert src.delta_fallbacks == 0
        finally:
            parent.stop()
            node.stop()
            ps.stop()

    def test_offers_are_async_off_the_refresh_path(self, devices8, rng):
        """Review fix: request_offers() returns immediately and the
        fan-out lands on the node's own offer thread."""
        parent = RelayNode(rid=0, port=0, compress=False,
                           fanout=2).start()
        offers = []
        child = RelayNode(rid=1, port=0, compress=False,
                          on_offer=lambda: offers.append(1)).start()
        try:
            _publish(parent, rng.normal(size=8).astype(np.float32), ts=1)
            fetch_raw(parent.port, rport=child.port)
            t0 = time.monotonic()
            parent.request_offers()
            assert time.monotonic() - t0 < 0.1  # no inline fan-out
            deadline = time.monotonic() + 5.0
            while not offers and time.monotonic() < deadline:
                time.sleep(0.02)
            assert offers == [1]
        finally:
            parent.stop()
            child.stop()

    def test_compress_off_dense_change_ships_plain_full(self, rng):
        """Review fix: without the compression transform XFULL is
        FULL-sized anyway and only adds a basis requirement -- the
        substitution must be gated on compress."""
        node = RelayNode(rid=0, port=0, compress=False).start()
        try:
            w1 = rng.normal(size=256).astype(np.float32)
            w2 = (w1 * 1.5).astype(np.float32)
            _publish(node, w1, ts=1)
            _publish(node, w2, ts=2)
            hdr, payload = fetch_raw(node.port, have=1)
            assert hdr["wenc"] == "full"
            got = wiredelta.decode("full", payload, 0, None, None)
            assert got.tobytes() == w2.tobytes()
        finally:
            node.stop()

    def test_fenced_child_adopts_epoch_from_parent(self, devices8, rng):
        """The other direction: a STALE child is REJECT_FENCED by its
        parent, adopts the minted epoch, and self-heals through the
        root."""
        ps, d = start_ps(devices8)
        parent = RelayNode(rid=0, port=0).start()
        node = RelayNode(rid=1, port=0)
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            push_once(cl, 0, d)
            RelaySource("127.0.0.1", ps.port, parent).subscribe(0)
            parent.epoch = 5
            node.epoch = 1  # deposed view
            src = RelaySource("127.0.0.1", ps.port, node,
                              parent=("127.0.0.1", parent.port), rid=1,
                              retry_parent_s=30.0)
            got = src.subscribe(1)
            assert got is not None and got[0] == 1
            assert node.epoch == 5  # adopted the minted epoch
        finally:
            parent.stop()
            node.stop()
            ps.stop()


# --------------------------------------------------------- egress + offers
class TestEgressScaling:
    N_REPLICAS = 8
    VERSIONS = 6

    def _drive(self, devices, relay: bool):
        """N in-process replica sources, driven in topo order per
        version; returns the PS's SUBSCRIBE model-payload bytes."""
        ps, d = start_ps(devices, d=256)
        cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
        nodes, sources = [], []
        try:
            for rid in range(self.N_REPLICAS):
                node = RelayNode(rid=rid, port=0).start()
                p = parent_index(rid, 2)
                parent = (None if (not relay or p == ROOT)
                          else ("127.0.0.1", nodes[p].port))
                nodes.append(node)
                sources.append(RelaySource(
                    "127.0.0.1", ps.port, node, parent=parent, rid=rid))
            seed_rng = np.random.default_rng(2)
            wires = set()
            for v in range(self.VERSIONS):
                push_once(cl, 0, d, seed_rng=seed_rng)
                for rid in range(self.N_REPLICAS):  # topo order by plan
                    got = sources[rid].subscribe(rid)
                    assert got[0] == v + 1
                    wires.add(got[1].tobytes())
                assert len(wires) == v + 1  # all replicas byte-agree
            return ps.subscribe_model_bytes
        finally:
            for node in nodes:
                node.stop()
            ps.stop()

    def test_ps_egress_is_sublinear_with_relay_on(self, devices8):
        """THE acceptance: direct SUBSCRIBE is the N x control; the
        relay tree (fanout 2 -> 2 root children of 8 replicas) cuts PS
        subscribe egress to roughly the root-children share."""
        direct = self._drive(devices8, relay=False)
        reset_totals()
        relayed = self._drive(devices8, relay=True)
        assert direct > 0
        assert relayed < 0.5 * direct, (relayed, direct)


class TestRootOfferPath:
    def test_ps_offers_wake_relay_replicas(self, devices8, rng):
        """A relay replica with a LONG poll interval still tracks the
        model closely: the PS's offer loop announces each version and
        the replica fetches on the offer, not the poll."""
        ps, d = start_ps(devices8)
        rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                           refresh_interval_s=30.0,  # poll ~ never
                           relay_port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            # first refresh registers the rport with the PS
            deadline = time.monotonic() + 10
            while rep._served is None and time.monotonic() < deadline:
                time.sleep(0.05)
            assert rep._served is not None
            push_once(cl, 0, d)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                served = rep._served
                if served is not None and served.ts >= 1:
                    break
                time.sleep(0.05)
            assert rep._served.ts >= 1, "offer never woke the replica"
            assert ps.relay_offers >= 1
        finally:
            rep.stop()
            ps.stop()


# ----------------------------------------------------------- chaos (seeded)
class TestInteriorKillAcceptance:
    @pytest.mark.chaos
    def test_sigkill_interior_node_children_rehome_to_root(
            self, devices8, tmp_path):
        """THE chaos acceptance (rides every chaos_sweep seed): a real
        3-process relay chain r0 <- r1 <- r2; r1 is SIGKILLed at a
        seeded point mid-distribution.  r2 must re-home to the root
        within the retry window and keep serving CRC-valid, current-
        epoch models -- never a torn or stale one."""
        rng_seed = np.random.default_rng(CHAOS_SEED)
        kill_after_version = int(rng_seed.integers(3, 7))
        ps, d = start_ps(devices8)
        procs = []
        try:
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["ASYNCTPU_FORCE_CPU"] = "1"
            env["PYTHONPATH"] = str(REPO)
            env["ASYNCTPU_ASYNC_SERVE_REFRESH_INTERVAL_S"] = "0.02"
            env["ASYNCTPU_ASYNC_RELAY_PARENT_RETRY_S"] = "1.0"
            relay_ports = []
            for rid in range(3):
                cmd = [sys.executable, "-m",
                       "asyncframework_tpu.serving.cli", "replica",
                       "--ps", f"127.0.0.1:{ps.port}",
                       "--host", "127.0.0.1", "--rid", str(rid),
                       "--relay-port", "0"]
                if rid > 0:
                    cmd += ["--relay-parent",
                            f"127.0.0.1:{relay_ports[rid - 1]}"]
                p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     stderr=subprocess.DEVNULL,
                                     env=env, cwd=str(REPO), text=True)
                procs.append(p)
                line = p.stdout.readline()
                assert line, f"replica {rid} never announced"
                relay_ports.append(json.loads(line)["relay_port"])
            ps_client = ps_dcn.PSClient("127.0.0.1", ps.port,
                                        pull_mode="full")
            crc_by_ts = {}
            seed_rng = np.random.default_rng(CHAOS_SEED + 1)
            killed = False
            for v in range(1, 13):
                push_once(ps_client, 0, d, seed_rng=seed_rng)
                snap = ps._model_snap()
                crc_by_ts[snap.ts] = snap.crc
                if v == kill_after_version and not killed:
                    os.kill(procs[1].pid, signal.SIGKILL)
                    killed = True
                time.sleep(0.25)
            assert killed
            # r2 (the killed node's child) must converge to the current
            # version within the re-home window
            deadline = time.monotonic() + 15.0
            final_ts = ps._clock
            status = None
            while time.monotonic() < deadline:
                hdr, _ = fetch_raw(relay_ports[2])
                if hdr.get("op") == "RELAY_MODEL" \
                        and int(hdr["ts"]) >= final_ts:
                    status = hdr
                    break
                time.sleep(0.2)
            assert status is not None, \
                f"r2 never reached ts {final_ts} after interior kill"
            # CRC assert: what r2 re-serves is exactly what the PS
            # published for that version -- never torn
            ts = int(status["ts"])
            assert ts in crc_by_ts
            assert int(status["crc"]) == crc_by_ts[ts]
        finally:
            for p in procs:
                try:
                    p.kill()
                except OSError:
                    pass
            ps.stop()


# --------------------------------------------------------------- replica API
class TestReplicaIntegration:
    def test_replica_status_carries_relay_section(self, devices8, rng):
        ps, d = start_ps(devices8)
        rep = ModelReplica("127.0.0.1", ps.port, rid=0,
                           refresh_interval_s=0.02,
                           relay_port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            push_once(cl, 0, d)
            deadline = time.monotonic() + 10
            while rep._served is None and time.monotonic() < deadline:
                time.sleep(0.05)
            st = rep.status()
            assert "relay" in st
            assert st["relay"]["port"] == rep._relay_node.port
            assert st["relay"]["parent"] is None
        finally:
            rep.stop()
            ps.stop()

    def test_relay_off_replica_has_no_relay_surface(self, devices8):
        ps, _d = start_ps(devices8)
        rep = ModelReplica("127.0.0.1", ps.port, rid=0)
        try:
            assert rep._relay_node is None
            assert "relay" not in rep.status()
        finally:
            rep.stop()
            ps.stop()
