"""Kubernetes adapter (SURVEY §2.4 "Resource managers" row): manifest
rendering for the standalone daemons + per-app submission Jobs.

Parity bar: ``resource-managers/kubernetes/.../submit/
KubernetesClientApplication.scala:90,188`` -- the reference builds driver
pod specs from submissions; this build renders the equivalent specs as
apply-able YAML (generate-then-kubectl, no in-process API client).
Rendering is pure, so every property is testable without a cluster.
"""

import subprocess
import sys

import pytest
import yaml

from asyncframework_tpu.deploy import k8s


def _load_all(text):
    return [d for d in yaml.safe_load_all(text) if d]


class TestMasterRendering:
    def test_single_master(self):
        objs = k8s.render_master()
        kinds = [o["kind"] for o in objs]
        assert kinds == ["PersistentVolumeClaim", "Deployment", "Service"]
        dep = objs[1]
        assert dep["spec"]["replicas"] == 1
        cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
        assert "--ha" not in cmd
        assert "--persistence-dir" in cmd
        svc = objs[2]
        ports = {p["name"]: p["port"] for p in svc["spec"]["ports"]}
        assert ports == {"rpc": k8s.RPC_PORT, "ui": k8s.UI_PORT}
        # the UI must bind beyond pod loopback or the Service's ui port
        # routes to nothing (ISSUE 1 satellite)
        assert cmd[cmd.index("--ui-host") + 1] == "0.0.0.0"

    def test_ha_masters_share_rwx_state(self):
        objs = k8s.render_master(ha_replicas=3)
        pvc, dep, _svc = objs
        assert pvc["spec"]["accessModes"] == ["ReadWriteMany"]
        assert dep["spec"]["replicas"] == 3
        cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
        assert "--ha" in cmd
        mounts = dep["spec"]["template"]["spec"]["containers"][0][
            "volumeMounts"
        ]
        assert mounts[0]["mountPath"] == "/state"

    def test_bad_replicas_rejected(self):
        with pytest.raises(ValueError):
            k8s.render_master(ha_replicas=0)


class TestWorkerRendering:
    def test_workers_point_at_master_service(self):
        (dep,) = k8s.render_workers(8, cores=2)
        assert dep["spec"]["replicas"] == 8
        c = dep["spec"]["template"]["spec"]["containers"][0]
        assert f"async-master:{k8s.RPC_PORT}" in c["command"]
        assert c["command"][c["command"].index("--cores") + 1] == "2"
        assert c["resources"] == {"limits": {"google.com/tpu": 1}}

    def test_custom_resources_pass_through(self):
        (dep,) = k8s.render_workers(
            2, resources={"limits": {"cpu": "4"}}
        )
        c = dep["spec"]["template"]["spec"]["containers"][0]
        assert c["resources"] == {"limits": {"cpu": "4"}}


class TestAppJob:
    def test_job_runs_master_cli_with_supervise(self):
        (job,) = k8s.render_app_job(
            "eps", ["--quiet", "asgd", "synthetic", "synthetic", "16",
                    "4096", "8", "400", "1.0", "2147483647", "0.3", "0.5",
                    "50", "0", "42"],
            num_processes=3,
        )
        assert job["kind"] == "Job"
        assert job["spec"]["backoffLimit"] == 0
        spec = job["spec"]["template"]["spec"]
        assert spec["restartPolicy"] == "Never"
        cmd = spec["containers"][0]["command"]
        assert "--master" in cmd and f"async-master:{k8s.RPC_PORT}" in cmd
        assert "--supervise" in cmd
        assert cmd[cmd.index("--processes") + 1] == "3"
        assert cmd[-1] == "42"  # recipe argv rides verbatim at the tail

    def test_empty_argv_rejected(self):
        with pytest.raises(ValueError):
            k8s.render_app_job("x", [], 2)


class TestServingRendering:
    def test_serving_tier_topology(self):
        objs = k8s.render_serving(3, ps="async-ps:7078")
        kinds = [o["kind"] for o in objs]
        assert kinds == ["Deployment", "Service", "Deployment"]
        fe_dep, svc, rep_dep = objs
        fe_cmd = fe_dep["spec"]["template"]["spec"]["containers"][0][
            "command"
        ]
        assert "frontend" in fe_cmd
        ports = {p["name"]: p["port"] for p in svc["spec"]["ports"]}
        assert ports == {"predict": k8s.SERVE_PORT}
        assert rep_dep["spec"]["replicas"] == 3
        rep_cmd = rep_dep["spec"]["template"]["spec"]["containers"][0][
            "command"
        ]
        # replicas SUBSCRIBE to the given PS and HELLO the frontend
        # Service -- pod churn re-registers, scaling reads is kubectl
        # scale on this Deployment
        assert rep_cmd[rep_cmd.index("--ps") + 1] == "async-ps:7078"
        assert (rep_cmd[rep_cmd.index("--frontend") + 1]
                == f"async-serve:{k8s.SERVE_PORT}")

    def test_relay_tier_is_statefulset_with_headless_service(self):
        """ISSUE 12: relay_fanout > 0 renders the relaycast tier -- a
        StatefulSet (ordinal = tree position) behind a headless Service
        (stable per-pod DNS the children dial), the replica CLI in
        --relay-auto mode, and the fanout pinned via --conf so every
        pod computes the same deterministic tree."""
        objs = k8s.render_serving(5, ps="async-ps:7078", relay_fanout=2)
        kinds = [o["kind"] for o in objs]
        assert kinds == ["Deployment", "Service", "StatefulSet",
                         "Service"]
        sts, headless = objs[2], objs[3]
        assert sts["spec"]["serviceName"] == "async-serve-relay"
        assert sts["spec"]["replicas"] == 5
        assert headless["spec"]["clusterIP"] == "None"
        ports = {p["name"]: p["port"]
                 for p in headless["spec"]["ports"]}
        assert ports == {"relay": k8s.RELAY_PORT}
        cmd = sts["spec"]["template"]["spec"]["containers"][0]["command"]
        assert "--relay-auto" in cmd
        assert cmd[cmd.index("--relay-port") + 1] == str(k8s.RELAY_PORT)
        assert cmd[cmd.index("--relay-service") + 1] == \
            "async-serve-relay"
        assert "async.relay.fanout=2" in cmd
        # the relay port is exposed on the pod next to the predict port
        cports = [p["containerPort"] for p in
                  sts["spec"]["template"]["spec"]["containers"][0][
                      "ports"]]
        assert k8s.RELAY_PORT in cports and k8s.SERVE_PORT + 1 in cports

    def test_relay_off_is_byte_identical_topology(self):
        assert (k8s.render_serving(3, ps="x:1")
                == k8s.render_serving(3, ps="x:1", relay_fanout=0))

    def test_cluster_bundle_gains_relay_tier(self):
        files = k8s.render_cluster(2, serving=4, serving_ps="ps:7078",
                                   relay_fanout=2)
        objs = _load_all(files["serving.yaml"])
        assert "StatefulSet" in [o["kind"] for o in objs]

    def test_serving_requires_ps_and_replicas(self):
        with pytest.raises(ValueError):
            k8s.render_serving(0, ps="x:1")
        with pytest.raises(ValueError):
            k8s.render_serving(2, ps="")
        with pytest.raises(ValueError):
            k8s.render_serving(2, ps="x:1", relay_fanout=-1)

    def test_cluster_bundle_gains_serving(self):
        files = k8s.render_cluster(2, serving=2, serving_ps="ps:7078")
        assert "serving.yaml" in files
        objs = _load_all(files["serving.yaml"])
        assert [o["kind"] for o in objs] == ["Deployment", "Service",
                                             "Deployment"]


class TestClusterBundle:
    def test_bundle_parses_and_covers_topology(self):
        files = k8s.render_cluster(4, ha_replicas=2, topic_server=True)
        assert set(files) == {"master.yaml", "workers.yaml",
                              "topic-server.yaml"}
        for text in files.values():
            objs = _load_all(text)  # valid YAML, k8s-shaped
            for o in objs:
                assert {"apiVersion", "kind", "metadata", "spec"} <= set(o)
                assert o["metadata"]["labels"][
                    "app.kubernetes.io/part-of"
                ] == "asyncframework-tpu"
        ts = _load_all(files["topic-server.yaml"])
        assert ts[1]["spec"]["replicas"] == 1  # single-writer discipline

    def test_cli_render_writes_files(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-m", "asyncframework_tpu.deploy.k8s",
             "render", "--out", str(tmp_path), "--workers", "3",
             "--ha", "2", "--topic-server"],
            capture_output=True, text=True,
        )
        assert out.returncode == 0, out.stderr
        written = sorted(p.name for p in tmp_path.iterdir())
        assert written == ["master.yaml", "topic-server.yaml",
                           "workers.yaml"]
        for p in tmp_path.iterdir():
            assert _load_all(p.read_text())

    def test_cli_app_job(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-m", "asyncframework_tpu.deploy.k8s",
             "app", "--out", str(tmp_path), "--name", "eps",
             "--processes", "3", "--",
             "--quiet", "asgd", "synthetic", "synthetic", "16", "4096",
             "8", "400", "1.0", "2147483647", "0.3", "0.5", "50", "0",
             "42"],
            capture_output=True, text=True,
        )
        assert out.returncode == 0, out.stderr
        (job,) = _load_all((tmp_path / "app-eps.yaml").read_text())
        assert job["kind"] == "Job"


class TestObserverRendering:
    """Cluster-observer tier (ISSUE 14): collector Deployment +
    fleet-view Service + run-history PVC + one metrics Service per
    scraped role, consuming the PR 7 scrape wiring (METRICS_PORT env +
    annotations the pod templates already ship)."""

    def test_render_observer_objects(self):
        objs = k8s.render_observer()
        kinds = [(o["kind"], o["metadata"]["name"]) for o in objs]
        # one metrics Service per default scrape app
        metric_svcs = [n for (kind, n) in kinds
                       if kind == "Service"
                       and n.startswith("async-metrics-")]
        assert len(metric_svcs) == len(k8s.OBSERVER_SCRAPE_APPS)
        assert ("PersistentVolumeClaim",
                "async-observer-history") in kinds
        assert ("Deployment", "async-observer") in kinds
        assert ("Service", "async-observer") in kinds
        dep = next(o for o in objs if o["kind"] == "Deployment")
        assert dep["spec"]["replicas"] == 1  # ONE history-store writer
        c = dep["spec"]["template"]["spec"]["containers"][0]
        cmd = c["command"]
        assert "asyncframework_tpu.metrics.observer" in cmd
        ep = cmd[cmd.index("--endpoints") + 1]
        # every metrics Service appears in the collector's target list
        # at the telemetry port the pods actually listen on
        for (name, role, _app) in k8s.OBSERVER_SCRAPE_APPS:
            assert (f"{name}={role}@async-metrics-{name}:"
                    f"{k8s.METRICS_PORT}") in ep
        assert cmd[cmd.index("--history-dir") + 1] == "/history"
        assert any(v["mountPath"] == "/history"
                   for v in c["volumeMounts"])
        # the metrics Services route the SAME port the pod wiring binds
        for o in objs:
            if o["kind"] == "Service" and \
                    o["metadata"]["name"].startswith("async-metrics-"):
                (port,) = o["spec"]["ports"]
                assert port["port"] == k8s.METRICS_PORT
                assert port["targetPort"] == k8s.METRICS_PORT

    def test_metrics_services_select_the_annotated_pods(self):
        """The consumed wiring is real: each metrics Service's selector
        matches a pod template that carries the scrape annotations and
        the telemetry-port env."""
        rendered = (k8s.render_master() + k8s.render_workers(2)
                    + k8s.render_serving(2, "ps:1"))
        pods = {}
        for o in rendered:
            if o["kind"] in ("Deployment", "StatefulSet"):
                tpl = o["spec"]["template"]
                pods[tpl["metadata"]["labels"]["app"]] = tpl
        for o in k8s.render_observer():
            if o["kind"] != "Service" or not \
                    o["metadata"]["name"].startswith("async-metrics-"):
                continue
            app = o["spec"]["selector"]["app"]
            assert app in pods, f"metrics Service selects unknown {app}"
            tpl = pods[app]
            assert tpl["metadata"]["annotations"][
                "prometheus.io/port"] == str(k8s.METRICS_PORT)
            env = {e["name"]: e["value"] for c in
                   tpl["spec"]["containers"] for e in c.get("env", [])}
            assert env["ASYNCTPU_ASYNC_METRICS_PORT"] == \
                str(k8s.METRICS_PORT)

    def test_prof_env_rides_every_metrics_pod(self):
        """async.prof.* plumbs through the one metrics-env choke point:
        every telemetry-serving pod boots with profiling enabled at the
        fleet-gentle rate (ISSUE 18), and the env spellings match the
        registered ConfigEntries."""
        from asyncframework_tpu.conf import AsyncConf, registry

        assert "async.prof.enabled" in registry()
        assert "async.prof.hz" in registry()
        prefix = AsyncConf.ENV_PREFIX
        rendered = (k8s.render_master() + k8s.render_workers(2)
                    + k8s.render_serving(2, "ps:1")
                    + k8s.render_ps_shards(2, 16, 1024))
        seen = 0
        for o in rendered:
            if o["kind"] not in ("Deployment", "StatefulSet"):
                continue
            tpl = o["spec"]["template"]
            env = {e["name"]: e["value"] for c in
                   tpl["spec"]["containers"] for e in c.get("env", [])}
            if "ASYNCTPU_ASYNC_METRICS_PORT" not in env:
                continue
            seen += 1
            assert env[prefix + "ASYNC_PROF_ENABLED"] == "1"
            assert env[prefix + "ASYNC_PROF_HZ"] == str(k8s.PROF_FLEET_HZ)
        assert seen >= 4  # master, workers, serving, shards all covered

    def test_cluster_bundle_with_observer_and_shards(self):
        files = k8s.render_cluster(2, observer=True, ps_shards=2,
                                   ps_d=16, ps_n=1024)
        assert "observer.yaml" in files
        objs = _load_all(files["observer.yaml"])
        names = {o["metadata"]["name"] for o in objs}
        # per-shard metrics Services ride along when shards render
        assert "async-metrics-ps-shard-0" in names
        assert "async-metrics-ps-shard-1" in names
        # and without the flag nothing observer-shaped renders
        assert "observer.yaml" not in k8s.render_cluster(2)
