"""Long-context attention tests on the 8-device virtual CPU mesh.

Ring attention and Ulysses all-to-all sequence parallelism are net-new
TPU-first scope (the reference has no sequence dimension at all -- SURVEY.md
section 2.2); correctness is exactness against single-device full softmax
attention, including gradients through the collectives.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from asyncframework_tpu.parallel import (
    make_mesh,
    reference_attention,
    ring_attention,
    ulysses_attention,
)


def make_qkv(rng, b=2, t=64, h=8, d=16):
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, t, h, d)).astype(np.float32)
    v = rng.normal(size=(b, t, h, d)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.fixture(scope="module")
def sp_mesh():
    import jax as _jax

    return make_mesh(8, axis_names=("sp",), devices=_jax.devices()[:8])


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, rng, sp_mesh, causal):
        q, k, v = make_qkv(rng)
        want = reference_attention(q, k, v, causal=causal)
        got = ring_attention(q, k, v, sp_mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_single_device_mesh_degenerates(self, rng):
        mesh = make_mesh(1, axis_names=("sp",), devices=jax.devices()[:1])
        q, k, v = make_qkv(rng, t=32)
        got = ring_attention(q, k, v, mesh)
        want = reference_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_uneven_seq_rejected(self, rng, sp_mesh):
        q, k, v = make_qkv(rng, t=30)  # 30 % 8 != 0
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, sp_mesh)

    def test_mismatched_qk_seq_rejected(self, rng, sp_mesh):
        """tq != tk would make the block-position causal mask silently wrong
        (reference aligns bottom-right); must be a hard error."""
        q, _, _ = make_qkv(rng, t=32)
        _, k, v = make_qkv(rng, t=64)
        with pytest.raises(ValueError, match="equal q/k seq lens"):
            ring_attention(q, k, v, sp_mesh, causal=True)

    def test_bf16_inputs_accumulate_in_f32(self, rng, sp_mesh):
        """bf16 inputs: ring's error vs an fp32 oracle must stay in the same
        band as single-shot bf16 attention (fp32 running state), not grow
        with ring steps."""
        q, k, v = make_qkv(rng, t=64)
        oracle = np.asarray(reference_attention(q, k, v))
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        ring_err = np.abs(
            np.asarray(ring_attention(qb, kb, vb, sp_mesh), np.float32)
            - oracle
        ).max()
        ref_err = np.abs(
            np.asarray(reference_attention(qb, kb, vb), np.float32) - oracle
        ).max()
        assert ring_err < 2.5 * ref_err + 1e-3
        # and the output dtype follows the inputs
        assert ring_attention(qb, kb, vb, sp_mesh).dtype == jnp.bfloat16

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.slow
    def test_gradients_match_reference(self, rng, sp_mesh, causal):
        """Differentiability through ppermute + fori_loop (training path)."""
        q, k, v = make_qkv(rng, b=1, t=32, h=4, d=8)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, sp_mesh, causal=causal) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
            )

    def test_causal_first_positions_attend_self_only(self, rng, sp_mesh):
        """Row 0 of causal attention must equal v[0] exactly (only itself)."""
        q, k, v = make_qkv(rng, b=1, t=64, h=8, d=16)
        out = ring_attention(q, k, v, sp_mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(out[0, 0]), np.asarray(v[0, 0]), rtol=1e-5, atol=1e-6
        )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, rng, sp_mesh, causal):
        q, k, v = make_qkv(rng)  # h=8 divisible by 8 devices
        want = reference_attention(q, k, v, causal=causal)
        got = ulysses_attention(q, k, v, sp_mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_head_divisibility_enforced(self, rng, sp_mesh):
        q, k, v = make_qkv(rng, h=6)
        with pytest.raises(ValueError, match="heads"):
            ulysses_attention(q, k, v, sp_mesh)

    def test_agrees_with_ring(self, rng, sp_mesh):
        q, k, v = make_qkv(rng)
        a = ring_attention(q, k, v, sp_mesh, causal=True)
        b = ulysses_attention(q, k, v, sp_mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


class TestPallasBlockKernel:
    """ring_attention with the hand-tiled chunk_attention Pallas kernel
    (interpret mode on CPU) must agree with the oracle exactly like the
    XLA block path."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_block_matches_reference(self, rng, sp_mesh, causal):
        q, k, v = (
            rng.normal(size=(2, 32, 2, 16)).astype(np.float32)
            for _ in range(3)
        )
        got = ring_attention(
            q, k, v, sp_mesh, causal=causal, block_kernel="pallas"
        )
        want = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_unknown_kernel_rejected(self, rng, sp_mesh):
        q = rng.normal(size=(1, 8, 1, 4)).astype(np.float32)
        with pytest.raises(ValueError):
            ring_attention(q, q, q, sp_mesh, block_kernel="nope")


class TestChunkAttentionKernel:
    def test_stats_match_oracle(self, rng):
        import math

        import jax.numpy as jnp

        from asyncframework_tpu.ops.pallas_kernels import chunk_attention

        B, T, H, D = 2, 24, 3, 20
        q = rng.normal(size=(B, T, H, D)).astype(np.float32)
        k = rng.normal(size=(B, 18, H, D)).astype(np.float32)
        v = rng.normal(size=(B, 18, H, D)).astype(np.float32)
        mask = rng.random((T, 18)) > 0.3
        o, m, l = chunk_attention(q, k, v, mask, interpret=True)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(D)
        s = jnp.where(jnp.asarray(mask)[None, None], s, -1e30)
        mw = s.max(-1)
        p = jnp.exp(s - mw[..., None])
        np.testing.assert_allclose(np.asarray(m), np.asarray(mw), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(l), np.asarray(p.sum(-1)), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(o),
            np.asarray(jnp.einsum("bhqk,bkhd->bqhd", p, v)),
            rtol=1e-4, atol=1e-5,
        )


class TestUlyssesPallas:
    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_matches_reference(self, rng, sp_mesh, causal):
        from asyncframework_tpu.parallel import ulysses_attention

        q, k, v = (
            rng.normal(size=(2, 32, 8, 16)).astype(np.float32)
            for _ in range(3)
        )
        got = ulysses_attention(
            q, k, v, sp_mesh, causal=causal, block_kernel="pallas"
        )
        want = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_blockwise_fold(self, rng, sp_mesh, causal):
        """pallas_block smaller than the sequence exercises the K/V fold
        loop (the VMEM-bounded path real long sequences take)."""
        from asyncframework_tpu.parallel import ulysses_attention

        q, k, v = (
            rng.normal(size=(1, 32, 8, 8)).astype(np.float32)
            for _ in range(3)
        )
        got = ulysses_attention(
            q, k, v, sp_mesh, causal=causal, block_kernel="pallas",
            pallas_block=8,
        )
        want = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )
