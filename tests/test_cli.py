"""CLI tests: the 13-positional-arg submit surface.

Parity: the reference's drivers are launched via spark-submit with 13
positional args (``README.md:46``); recipes must be reusable verbatim here
modulo the jar/class prefix.
"""

import json

import numpy as np
import pytest

from asyncframework_tpu import cli


def recipe(driver, path="synthetic", file="x", d=16, N=512, parts=8,
           iters=40, gamma=1.0, taw=2**31 - 1, b=0.3, bucket=0.5,
           pfreq=10, coeff=0.0, seed=42, extra=()):
    return [driver, path, file, str(d), str(N), str(parts), str(iters),
            str(gamma), str(taw), str(b), str(bucket), str(pfreq),
            str(coeff), str(seed), *extra]


def run_cli(capsys, argv):
    rc = cli.main(argv)
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1]), out[:-1]


class TestDrivers:
    @pytest.mark.parametrize("name,expect,accepted", [
        ("SparkASGDThread", "asgd", 30),        # async: accepted updates
        ("asgd-sync", "asgd-sync", 30 * 8),     # sync: rounds x workers
        ("SparkASAGAThread", "asaga", 30),
        ("SparkASAGASync", "asaga-sync", 30 * 8),
    ])
    def test_async_drivers_run(self, capsys, name, expect, accepted):
        summary, traj_lines = run_cli(
            capsys, recipe(name, iters=30, extra=("--quiet",))
        )
        assert summary["driver"] == expect
        assert summary["accepted"] == accepted
        # plumbing test, not a convergence test (those live in test_solvers)
        assert np.isfinite(summary["final_objective"])
        assert not traj_lines  # --quiet

    @pytest.mark.parametrize("name,gamma", [
        ("asgd-fused", 1.0), ("asaga-fused", 0.3),
    ])
    def test_fused_drivers_run(self, capsys, name, gamma):
        summary, _ = run_cli(
            capsys, recipe(name, iters=32, gamma=gamma, extra=("--quiet",))
        )
        assert summary["driver"] == name
        assert summary["accepted"] >= 32
        assert summary["dropped"] == 0
        assert np.isfinite(summary["final_objective"])

    def test_fused_rejects_checkpoint_dir(self, tmp_path):
        with pytest.raises(SystemExit, match="checkpoint"):
            cli.main(recipe("asgd-fused", iters=5,
                            extra=("--checkpoint-dir", str(tmp_path))))

    def test_sgd_mllib_driver(self, capsys, tmp_path):
        # mllib baseline needs host arrays -> write a real libsvm file
        rs = np.random.default_rng(0)
        X = rs.normal(size=(256, 8)).astype(np.float32)
        w = rs.normal(size=(8,)).astype(np.float32)
        y = X @ w
        f = tmp_path / "tiny.libsvm"
        with open(f, "w") as fh:
            for i in range(256):
                feats = " ".join(f"{j+1}:{X[i, j]:.6f}" for j in range(8))
                fh.write(f"{y[i]:.6f} {feats}\n")
        summary, _ = run_cli(
            capsys,
            recipe("SparkSGDMLLIB", path=str(tmp_path), file="tiny.libsvm",
                   d=8, N=256, parts=8, iters=50, gamma=0.5,
                   extra=("--quiet",)),
        )
        assert summary["driver"] == "sgd-mllib"
        assert summary["iterations"] == 50

    def test_trajectory_printed_and_written(self, capsys, tmp_path):
        out_csv = tmp_path / "traj.csv"
        summary, traj_lines = run_cli(
            capsys,
            recipe("asgd", iters=20, extra=("--output", str(out_csv))),
        )
        assert traj_lines and traj_lines[0].startswith("(")
        lines = out_csv.read_text().splitlines()
        assert lines[0] == "ms,objective"
        assert len(lines) - 1 == len(traj_lines)

    def test_unknown_driver_rejected(self):
        with pytest.raises(SystemExit):
            cli.main(recipe("SparkNotADriver"))

    def test_missing_file_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="no such data file"):
            cli.main(recipe("asgd", path=str(tmp_path), file="nope.libsvm"))

    def test_conf_overlay(self, capsys):
        summary, _ = run_cli(
            capsys,
            recipe("asgd", iters=20, taw=0,
                   extra=("--quiet", "--conf", "async.taw=2147483647")),
        )
        # overlay lifted taw back to infinite: nothing dropped
        assert summary["dropped"] == 0


class TestObservabilityFlags:
    def test_event_log_and_report(self, capsys, tmp_path):
        log = tmp_path / "run.jsonl"
        report = tmp_path / "run.html"
        summary, _ = run_cli(capsys, recipe(
            "asgd", iters=30,
            extra=("--quiet", "--event-log", str(log), "--report", str(report)),
        ))
        assert summary["accepted"] == 30
        assert summary["report"] == str(report)
        assert log.exists()
        html = report.read_text()
        assert "Summary" in html and "Objective" in html

    def test_report_requires_event_log(self, tmp_path):
        with pytest.raises(SystemExit):
            cli.main(recipe("asgd", iters=5,
                            extra=("--report", str(tmp_path / "r.html"))))

    def test_stale_read_flag(self, capsys):
        summary, _ = run_cli(capsys, recipe(
            "asgd", iters=30, extra=("--quiet", "--stale-read", "2"),
        ))
        assert summary["accepted"] == 30

    def test_stale_read_rejected_for_sync(self):
        with pytest.raises(SystemExit):
            cli.main(recipe("asgd-sync", iters=5, extra=("--stale-read", "1")))

    def test_speculation_flag_smoke(self, capsys):
        summary, _ = run_cli(capsys, recipe(
            "asgd-sync", iters=10, extra=("--quiet", "--speculation"),
        ))
        assert summary["accepted"] == 10 * 8
