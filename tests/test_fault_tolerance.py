"""Fault-tolerance layer: blacklist, speculation, shard recovery.

Parity targets (SURVEY.md section 5): ``BlacklistTracker.scala`` windowed
failure counting with timed expiry, ``TaskSetManager.checkSpeculatableTasks``
quantile/multiplier policy, and the executor-loss -> recompute-elsewhere
story (lineage recomputation becomes explicit shard re-placement here).
All policy logic is tested with a ManualClock / pure inputs (the
``DAGSchedulerSuite`` zero-threads style), then integrated against the real
thread-backed engine.
"""

import threading
import time

import numpy as np
import pytest

from asyncframework_tpu.engine import (
    BlacklistTracker,
    ExecutorPool,
    JobScheduler,
    ShardRecovery,
    SpeculationMonitor,
    find_speculatable,
    plan_reassignment,
)
from asyncframework_tpu.engine.scheduler import ASYNC
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.utils.clock import ManualClock


class TestBlacklistTracker:
    def test_blacklists_after_max_failures(self):
        clock = ManualClock()
        bl = BlacklistTracker(max_failures=2, timeout_ms=1000, clock=clock)
        bl.record_failure(3)
        assert not bl.is_blacklisted(3)
        bl.record_failure(3)
        assert bl.is_blacklisted(3)
        assert bl.blacklisted_workers() == [3]
        assert not bl.is_blacklisted(0)

    def test_expires_after_timeout(self):
        clock = ManualClock()
        bl = BlacklistTracker(max_failures=1, timeout_ms=500, clock=clock)
        bl.record_failure(1)
        assert bl.is_blacklisted(1)
        clock.advance(501)
        assert not bl.is_blacklisted(1)

    def test_window_prunes_old_failures(self):
        clock = ManualClock()
        bl = BlacklistTracker(
            max_failures=2, timeout_ms=10_000, window_ms=100, clock=clock
        )
        bl.record_failure(5)
        clock.advance(200)  # first failure falls out of the window
        bl.record_failure(5)
        assert not bl.is_blacklisted(5)
        assert bl.failure_count(5) == 1

    def test_scheduler_replaces_blacklisted_executor(self):
        """After a worker is blacklisted, the next launch gets a fresh
        executor for that slot (the reference's schedule-elsewhere analog)."""
        bl = BlacklistTracker(max_failures=2, timeout_ms=60_000)
        sched = JobScheduler(num_workers=2, max_task_failures=10, blacklist=bl)
        sched.set_mode(ASYNC)
        try:
            calls = {"n": 0}

            def flaky():
                calls["n"] += 1
                if calls["n"] <= 2:
                    raise RuntimeError("boom")
                return "ok"

            before = sched.pool.executors[0]
            results = []
            waiter = sched.run_job({0: flaky}, lambda wid, r: results.append(r))
            waiter.await_result(timeout=30)
            assert results == ["ok"]
            # retries rotated the slot onto a replacement executor, and the
            # swap healed the slot (entry cleared -- no executor churn after)
            assert sched.pool.executors[0] is not before
            assert not bl.is_blacklisted(0)
            assert bl.failure_count(0) == 0
        finally:
            sched.shutdown()


class TestFindSpeculatable:
    def test_below_quantile_no_speculation(self):
        assert find_speculatable([100.0], {1: 10_000.0}, quantile=0.75) == []

    def test_slow_tail_selected(self):
        finished = [100.0, 110.0, 90.0, 105.0, 95.0, 100.0]
        running = {6: 500.0, 7: 120.0}
        out = find_speculatable(finished, running, quantile=0.5, multiplier=1.5)
        assert out == [6]

    def test_min_time_floor(self):
        # median is tiny; min_time_ms keeps short tasks from speculating
        out = find_speculatable([1.0, 1.0, 1.0], {3: 20.0}, quantile=0.5,
                                multiplier=1.5, min_time_ms=100.0)
        assert out == []

    def test_no_finished_no_speculation(self):
        assert find_speculatable([], {0: 1e9}) == []


class TestSpeculationIntegration:
    def test_speculative_copy_rescues_stuck_task(self):
        """7 fast tasks + 1 stuck task; the monitor launches a copy on a
        spare executor, the copy finishes, the job completes while the
        original is still blocked; the original's late result is dropped."""
        release = threading.Event()
        first_call = {"done": False}
        lock = threading.Lock()

        def make_fn(wid):
            if wid != 7:
                return lambda: wid
            def stuck():
                with lock:
                    first = not first_call["done"]
                    first_call["done"] = True
                if first:
                    release.wait(timeout=30)  # primary: blocked
                return wid                     # speculative copy: instant
            return stuck

        sched = JobScheduler(num_workers=8)
        sched.set_mode(ASYNC)
        monitor = SpeculationMonitor(
            sched, quantile=0.75, multiplier=1.5, min_time_ms=10.0
        )
        results = []
        res_lock = threading.Lock()

        def handler(wid, r):
            with res_lock:
                results.append((wid, r))

        try:
            # first job always blocks (warm-up parity); make it trivial
            sched.run_job({0: lambda: None}, lambda w, r: None)
            waiter = sched.run_job({w: make_fn(w) for w in range(8)}, handler)
            deadline = time.monotonic() + 30
            launched = []
            while not launched and time.monotonic() < deadline:
                time.sleep(0.05)
                launched = monitor.check_once()
            assert launched, "monitor never found the stuck task"
            waiter.await_result(timeout=30)
            with res_lock:
                assert sorted(r for _, r in results) == list(range(8))
            # releasing the primary must not double-merge worker 7
            release.set()
            time.sleep(0.3)
            with res_lock:
                assert len(results) == 8
            assert monitor.speculated_count() == 1
        finally:
            release.set()
            sched.shutdown()

    def test_failed_speculative_copy_is_dropped(self):
        """A crashing copy must not retry/abort the healthy primary's job."""
        release = threading.Event()
        calls = {"n": 0}
        lock = threading.Lock()

        def task():
            with lock:
                calls["n"] += 1
                first = calls["n"] == 1
            if first:
                release.wait(timeout=30)  # primary: slow but healthy
                return "primary"
            raise RuntimeError("speculative copy crashes")

        sched = JobScheduler(num_workers=2, max_task_failures=1)
        sched.set_mode(ASYNC)
        monitor = SpeculationMonitor(sched, quantile=0.5, min_time_ms=1.0)
        results = []
        try:
            sched.run_job({0: lambda: None}, lambda w, r: None)  # warm-up
            waiter = sched.run_job(
                {0: task, 1: lambda: "fast"}, lambda w, r: results.append(r)
            )
            deadline = time.monotonic() + 30
            while not monitor.check_once():
                assert time.monotonic() < deadline
                time.sleep(0.02)
            time.sleep(0.2)  # let the copy crash and be (dropped) reported
            assert waiter.failed is None, "copy failure aborted the job"
            release.set()
            waiter.await_result(timeout=30)
            assert sorted(results) == ["fast", "primary"]
        finally:
            release.set()
            sched.shutdown()

    def test_one_copy_per_task(self):
        release = threading.Event()

        def stuck():
            release.wait(timeout=30)
            return 0

        sched = JobScheduler(num_workers=2)
        sched.set_mode(ASYNC)
        monitor = SpeculationMonitor(sched, quantile=0.5, min_time_ms=1.0)
        try:
            sched.run_job({0: lambda: None}, lambda w, r: None)  # warm-up
            waiter = sched.run_job({0: stuck, 1: lambda: 1}, lambda w, r: None)
            deadline = time.monotonic() + 30
            while not monitor.check_once():
                assert time.monotonic() < deadline
                time.sleep(0.02)
            # further scans must not launch more copies for the same task
            assert monitor.check_once() == []
            assert monitor.speculated_count() == 1
            release.set()
            waiter.await_result(timeout=30)
        finally:
            release.set()
            sched.shutdown()


class TestShardRecovery:
    def test_plan_balanced_and_deterministic(self):
        plan = plan_reassignment(range(8), dead=[2, 5, 6])
        assert set(plan.moves) == {2, 5, 6}
        assert all(t not in {2, 5, 6} for t in plan.moves.values())
        # least-loaded round robin: three distinct survivors adopt
        assert len(set(plan.moves.values())) == 3
        assert plan == plan_reassignment(range(8), dead=[6, 2, 5])

    def test_no_survivors_raises(self):
        with pytest.raises(RuntimeError):
            plan_reassignment(range(2), dead=[0, 1])

    def test_move_shard_relocates_data(self, devices8):
        rs = np.random.default_rng(0)
        X = rs.normal(size=(64, 4)).astype(np.float32)
        y = rs.normal(size=(64,)).astype(np.float32)
        ds = ShardedDataset(X, y, num_workers=8, devices=devices8)
        rec = ShardRecovery(ds, devices8)
        lo, hi = ds.partition_cum[3], ds.partition_cum[4]

        moved = rec.move_shard(3, 0)
        assert moved.X.device == devices8[0]
        np.testing.assert_array_equal(np.asarray(moved.X), X[lo:hi])
        assert rec.owner(3) == 0
        # worker 0 now computes its own shard plus the adopted one
        assert [s.worker_id for s in rec.assignments(0)] == [0, 3]
        assert rec.assignments(3) == []

    def test_apply_plan(self, devices8):
        ds = ShardedDataset.generate_on_device(64, 4, 8, devices=devices8)
        rec = ShardRecovery(ds, devices8)
        plan = plan_reassignment(range(8), dead=[1, 4])
        rec.apply(plan)
        for sid, owner in plan.moves.items():
            assert rec.owner(sid) == owner
            assert rec.shard(sid).X.device == devices8[owner % 8]
        total = sum(len(rec.assignments(w)) for w in range(8))
        assert total == 8  # every shard still owned exactly once
