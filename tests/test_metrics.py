"""Metrics subsystem tests: bus fan-out, event-log write/replay, registry."""

import queue
import threading
import time

import pytest

from asyncframework_tpu.metrics import (
    CsvSink,
    EventLogReader,
    EventLogWriter,
    GradientMerged,
    JobEnd,
    JobStart,
    JsonlSink,
    Listener,
    ListenerBus,
    MetricsSystem,
    ModelSnapshot,
    RoundSubmitted,
    TaskEnd,
    WorkerLost,
)
from asyncframework_tpu.utils.clock import ManualClock


class Recorder(Listener):
    def __init__(self):
        self.events = []
        self.merges = []

    def on_event(self, event):
        self.events.append(event)

    def on_gradient_merged(self, event):
        self.merges.append(event)
        self.events.append(event)


def test_bus_sync_delivery_and_typed_hooks():
    bus = ListenerBus()
    rec = Recorder()
    bus.add_listener(rec)
    bus.post(JobStart(time_ms=1.0, job_id=0, worker_ids=(0, 1)))
    bus.post(GradientMerged(time_ms=2.0, worker_id=1, staleness=3,
                            accepted=True, iteration=7))
    assert len(rec.events) == 2
    assert len(rec.merges) == 1  # typed hook got the merge
    assert rec.merges[0].staleness == 3


def test_bus_async_dispatch_and_stop():
    bus = ListenerBus()
    rec = Recorder()
    bus.add_listener(rec)
    bus.start()
    for i in range(100):
        bus.post(TaskEnd(time_ms=float(i), job_id=0, worker_id=i % 4,
                         attempt=0, run_ms=1.0, succeeded=True))
    bus.stop()
    assert len(rec.events) == 100
    assert bus.dropped_events == 0


def test_bus_drops_when_full_without_blocking():
    bus = ListenerBus(capacity=4)
    slow_release = threading.Event()

    class Slow(Listener):
        def on_event(self, event):
            slow_release.wait(timeout=5.0)

    bus.add_listener(Slow())
    bus.start()
    for i in range(50):
        bus.post(JobEnd(time_ms=float(i), job_id=i, succeeded=True))
    assert bus.dropped_events > 0  # full queue dropped, post never blocked
    slow_release.set()
    bus.stop()


def test_bad_listener_does_not_kill_bus():
    bus = ListenerBus()

    class Bad(Listener):
        def on_event(self, event):
            raise RuntimeError("boom")

    rec = Recorder()
    bus.add_listener(Bad())
    bus.add_listener(rec)
    bus.post(JobEnd(time_ms=0.0, job_id=1, succeeded=True))
    assert len(rec.events) == 1


def test_eventlog_roundtrip(tmp_path):
    log = tmp_path / "run" / "events.jsonl"
    writer = EventLogWriter(log)
    bus = ListenerBus()
    bus.add_listener(writer)
    events = [
        RoundSubmitted(time_ms=1.0, round_idx=0, cohort=(0, 1, 2),
                       model_version=1),
        GradientMerged(time_ms=2.0, worker_id=0, staleness=0, accepted=True,
                       iteration=1, batch_size=64),
        GradientMerged(time_ms=3.0, worker_id=1, staleness=5, accepted=False,
                       iteration=1, batch_size=64),
        TaskEnd(time_ms=4.0, job_id=0, worker_id=2, attempt=0, run_ms=12.5,
                succeeded=True),
        WorkerLost(time_ms=5.0, worker_id=3, reason="heartbeat timeout"),
        ModelSnapshot(time_ms=6.0, iteration=1, objective=0.5),
    ]
    for ev in events:
        bus.post(ev)
    writer.close()

    replayed = list(EventLogReader(log).replay())
    assert replayed == events  # exact typed round-trip (tuples restored)


def test_eventlog_gzip_roundtrip(tmp_path):
    """.gz paths compress through the zlib codec and replay identically."""
    import gzip

    log = tmp_path / "events.jsonl.gz"
    writer = EventLogWriter(log)
    events = [
        ModelSnapshot(time_ms=float(i), iteration=i, objective=1.0 / (i + 1))
        for i in range(50)
    ]
    for ev in events:
        writer.on_event(ev)
    writer.close()
    with gzip.open(log) as f:  # actually gzip-framed on disk
        assert len(f.read().splitlines()) == 50
    assert list(EventLogReader(log).replay()) == events


def test_eventlog_gzip_survives_crash_without_close(tmp_path):
    """Per-event flush + torn-tail-tolerant replay: a writer that dies
    before close() (the crash-forensics case) loses nothing flushed."""
    log = tmp_path / "crash.jsonl.gz"
    writer = EventLogWriter(log)
    events = [ModelSnapshot(time_ms=float(i), iteration=i, objective=1.0)
              for i in range(40)]
    for ev in events:
        writer.on_event(ev)
    # no close(): simulated crash -- the gzip end-of-stream marker is absent
    assert list(EventLogReader(log).replay()) == events
    writer.close()


def test_eventlog_summary(tmp_path):
    log = tmp_path / "events.jsonl"
    writer = EventLogWriter(log)
    writer.on_event(RoundSubmitted(time_ms=0.0, round_idx=0, cohort=(0, 1),
                                   model_version=1))
    for i in range(10):
        writer.on_event(GradientMerged(
            time_ms=float(i), worker_id=i % 2, staleness=i % 4,
            accepted=(i % 4) <= 2, iteration=i))
    writer.on_event(ModelSnapshot(time_ms=10.0, iteration=10, objective=0.25))
    writer.close()
    s = EventLogReader(log).summary()
    assert s["rounds"] == 1
    assert s["merges"] == 10
    assert s["accepted"] == 8
    assert s["dropped_stale"] == 2
    assert s["staleness"]["max"] == 3
    assert s["trajectory"] == [(10.0, 0.25)]


def test_eventlog_skips_unknown_and_corrupt(tmp_path):
    log = tmp_path / "events.jsonl"
    log.write_text(
        '{"event":"JobEnd","time_ms":1.0,"job_id":0,"succeeded":true}\n'
        '{"event":"FutureEventType","time_ms":2.0,"x":1}\n'
        '{"event":"JobEnd","time_ms":3.0,"bad_field":true}\n'
        "\n"
        '{"event":"JobEnd","time_ms":4.0,"job_id":1,"succeeded":false}\n'
    )
    replayed = list(EventLogReader(log).replay())
    assert [e.job_id for e in replayed] == [0, 1]


def test_metrics_registry_and_collect():
    ms = MetricsSystem()
    c = ms.counter("updates.accepted")
    g = ms.gauge("queue.depth")
    h = ms.histogram("staleness")
    c.inc(5)
    g.set(3.0)
    for v in range(100):
        h.update(float(v % 10))
    ms.register_source("engine", lambda: {"workers": 8})
    out = ms.collect()
    assert out["updates.accepted"] == 5
    assert out["queue.depth"] == 3.0
    assert out["staleness"]["count"] == 100
    assert out["staleness"]["max"] == 9.0
    assert out["engine"] == {"workers": 8}
    # same name returns same instrument; wrong type raises
    assert ms.counter("updates.accepted") is c
    with pytest.raises(TypeError):
        ms.gauge("updates.accepted")


def test_metrics_source_error_isolated():
    ms = MetricsSystem()

    def bad():
        raise ValueError("nope")

    ms.register_source("bad", bad)
    out = ms.collect()
    assert "error" in str(out["bad"])


def test_sinks_csv_jsonl(tmp_path):
    ms = MetricsSystem()
    ms.counter("a").inc(1)
    ms.gauge("b.c").set(2.5)
    csv_path = tmp_path / "m.csv"
    jsonl_path = tmp_path / "m.jsonl"
    ms.add_sink(CsvSink(csv_path))
    ms.add_sink(JsonlSink(jsonl_path))
    ms.report()
    ms.counter("a").inc(1)
    ms.report()
    ms.stop()
    lines = csv_path.read_text().strip().splitlines()
    assert lines[0].startswith("time_ms")
    assert "a" in lines[0]
    assert len(lines) == 3  # header + 2 reports
    import json

    recs = [json.loads(l) for l in jsonl_path.read_text().splitlines()]
    assert recs[0]["a"] == 1 and recs[1]["a"] == 2


def test_polling_loop_with_manual_clock():
    clock = ManualClock()
    ms = MetricsSystem(clock=clock)
    ms.counter("ticks").inc()
    seen = []

    class Capture:
        def report(self, t, values):
            seen.append((t, dict(values)))

        def close(self):
            pass

    ms.add_sink(Capture())
    ms.start(period_s=1.0)
    for _ in range(3):
        time.sleep(0.05)  # let the loop reach clock.sleep
        clock.advance(1000.0)
    deadline = time.monotonic() + 5
    while len(seen) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    ms.stop()
    assert len(seen) >= 3


class TestHistory:
    def test_history_index_over_real_runs(self, tmp_path, devices8, tiny_problem):
        """FsHistoryProvider parity: two solver runs' event logs render to
        per-run reports plus an index; a torn log is listed as unreadable."""
        from asyncframework_tpu.metrics.history import build_history
        from asyncframework_tpu.solvers import ASGD, SolverConfig

        X, y, _ = tiny_problem
        logs = tmp_path / "logs"
        logs.mkdir()
        for i, name in enumerate(("run-a", "run-b")):
            cfg = SolverConfig(
                num_workers=8, num_iterations=40, gamma=1.0,
                taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
                printer_freq=20, coeff=0.0, seed=42 + i,
                calibration_iters=5, run_timeout_s=60.0,
                event_log=str(logs / f"{name}.jsonl"),
            )
            ASGD(X, y, cfg, devices=devices8).run()
        (logs / "torn.jsonl").write_text("{not json")
        index = build_history(logs)
        html_text = index.read_text()
        assert "run-a" in html_text and "run-b" in html_text
        assert "unreadable" in html_text
        assert (index.parent / "run-a.jsonl.html").exists()
        assert (index.parent / "run-b.jsonl.html").exists()
        assert "updates" in html_text

    def test_history_cli_usage(self, tmp_path, capsys):
        from asyncframework_tpu.metrics.history import main

        assert main([]) == 2
        d = tmp_path / "empty"
        d.mkdir()
        assert main([str(d)]) == 0


    def test_torn_tail_renders_valid_prefix(self, tmp_path, devices8,
                                            tiny_problem):
        """A crash-torn log (valid prefix + partial last line) must still
        render a report from the prefix, not show as unreadable."""
        from asyncframework_tpu.metrics.history import build_history
        from asyncframework_tpu.solvers import ASGD, SolverConfig

        X, y, _ = tiny_problem
        logs = tmp_path / "logs"
        logs.mkdir()
        log = logs / "crashed.jsonl"
        cfg = SolverConfig(
            num_workers=8, num_iterations=30, gamma=1.0, taw=2**31 - 1,
            batch_rate=0.3, bucket_ratio=0.5, printer_freq=10, coeff=0.0,
            seed=1, calibration_iters=5, run_timeout_s=60.0,
            event_log=str(log),
        )
        ASGD(X, y, cfg, devices=devices8).run()
        with open(log, "a") as f:
            f.write('{"event": "task_end", "worker')  # torn mid-write
        index = build_history(logs)
        html_text = index.read_text()
        assert "unreadable" not in html_text
        assert (index.parent / "crashed.jsonl.html").exists()


class TestLiveUI:
    """SparkUI parity: run state is served over HTTP DURING the run."""

    def test_fetch_status_mid_run(self, devices8):
        import json
        import threading
        import urllib.request

        from asyncframework_tpu.data import make_regression
        from asyncframework_tpu.metrics.live import active_servers
        from asyncframework_tpu.solvers import ASGD, SolverConfig

        X, y, _ = make_regression(2048, 16, seed=3)
        cfg = SolverConfig(
            num_workers=8, num_iterations=2000, gamma=0.5, batch_rate=0.3,
            bucket_ratio=0.5, printer_freq=100, seed=42,
            calibration_iters=10, run_timeout_s=120.0, ui_port=0,
        )
        holder = {}

        def run():
            holder["res"] = ASGD(X, y, cfg, devices=devices8).run()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        # discover the ephemeral port, then poll /api/status mid-run
        deadline = time.monotonic() + 30
        snap = None
        while time.monotonic() < deadline:
            servers = active_servers()
            if servers:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{servers[0].port}/api/status",
                        timeout=5,
                    ) as r:
                        snap = json.loads(r.read())
                except OSError:
                    snap = None
                if snap and snap["accepted"] > 0:
                    break
            time.sleep(0.01)
        t.join(timeout=60)
        assert snap is not None, "never fetched a live snapshot"
        assert snap["accepted"] > 0 and snap["rounds"] > 0
        assert "staleness" in snap and "workers" in snap
        assert len(snap["workers"]) == 8
        assert snap["queue_depth"] is not None
        res = holder["res"]
        assert res.extras.get("ui_port", 0) > 0
        # server is torn down with the run
        assert not active_servers()

    def test_html_index_served(self):
        import urllib.request

        from asyncframework_tpu.metrics.live import (
            LiveStateListener,
            LiveUIServer,
        )

        state = LiveStateListener(4)
        srv = LiveUIServer(state, port=0).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/", timeout=5
            ) as r:
                body = r.read().decode()
            assert "live run" in body
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=5
            ) as r:
                pass
        except urllib.error.HTTPError as e:
            assert e.code == 404
        finally:
            srv.stop()
