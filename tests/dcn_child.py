"""Child process for the two-process DCN bring-up test (test_multihost.py).

Runs on the forced-CPU platform with 2 virtual devices, initializes
``jax.distributed`` from the ASYNCTPU_* env vars through the framework's
``multihost`` wrapper, fences on the host barrier, and performs one global
psum whose result proves the collective crossed the process boundary.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from asyncframework_tpu.parallel import multihost  # noqa: E402


def main() -> None:
    active = multihost.ensure_initialized()  # env-driven (ASYNCTPU_*)
    pid, pc = multihost.process_info()
    multihost.sync_hosts("dcn-test")
    import jax.numpy as jnp

    # global psum: each device contributes (process_id + 1); with 2 procs x 2
    # devices the total is 2*1 + 2*2 = 6 everywhere
    local = jnp.full((jax.local_device_count(),), float(pid + 1))
    total = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(local)
    mesh = multihost.global_mesh()
    print(json.dumps({
        "active": bool(active),
        "pid": int(pid),
        "pc": int(pc),
        "devices": int(jax.device_count()),
        "local_devices": int(jax.local_device_count()),
        "psum": float(total[0]),
        "mesh_size": int(mesh.devices.size),
    }))


if __name__ == "__main__":
    main()
