"""Solver tests on the 8-device virtual CPU mesh.

Parity with the reference's algorithm test strategy
(``GradientDescentSuite``): loss decreases, exact semantics of the update
rules, plus async-specific properties (staleness bounds, history-table
consistency, straggler injection effects).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from asyncframework_tpu.data import make_classification, make_regression
from asyncframework_tpu.parallel import make_mesh
from asyncframework_tpu.solvers import ASAGA, ASGD, MiniBatchSGD, SolverConfig


@pytest.fixture(scope="module")
def problem():
    return make_regression(2048, 32, seed=3)


def small_cfg(**kw):
    defaults = dict(
        num_workers=8,
        num_iterations=300,
        gamma=1.0,
        taw=2**31 - 1,
        batch_rate=0.3,
        bucket_ratio=0.5,
        printer_freq=50,
        coeff=0.0,
        seed=42,
        calibration_iters=10,
        run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


class TestASGDAsync:
    def test_converges_and_bookkeeps(self, devices8, problem):
        X, y, _ = problem
        # Convergence under tau=inf depends on real thread timing: under heavy
        # CPU load a staleness spike can blow one run up (the algorithm is
        # working as specified -- unbounded-staleness ASGD at the stability
        # edge is not almost-surely convergent).  Retry once before failing.
        for attempt in range(2):
            res = ASGD(X, y, small_cfg(), devices=devices8).run()
            first, last = res.trajectory[0][1], res.trajectory[-1][1]
            if last < first * 0.5:
                break
        assert last < first * 0.5, res.trajectory
        assert res.accepted == 300
        assert res.rounds > 0
        assert res.updates_per_sec > 0
        # trajectory times monotonically nondecreasing
        times = [t for t, _ in res.trajectory]
        assert all(b >= a for a, b in zip(times, times[1:]))

    def test_taw_zero_drops_stale(self, devices8, problem):
        X, y, _ = problem
        res = ASGD(X, y, small_cfg(num_iterations=100, taw=0), devices=devices8).run()
        # with 8 concurrent workers and tau=0, some results must be stale
        assert res.accepted == 100
        assert res.dropped > 0

    def test_infinite_taw_drops_nothing(self, devices8, problem):
        X, y, _ = problem
        res = ASGD(X, y, small_cfg(num_iterations=100), devices=devices8).run()
        assert res.dropped == 0

    def test_logistic_loss_mode(self, devices8):
        X, y, _ = make_classification(2048, 16, seed=5)
        res = ASGD(
            X, y, small_cfg(loss="logistic", gamma=2.0, num_iterations=200),
            devices=devices8,
        ).run()
        assert res.trajectory[-1][1] < res.trajectory[0][1]

    def test_failing_worker_aborts_run(self, devices8, problem):
        """A deterministically-failing task must surface as an error, not a
        silent stall until run_timeout (job-abort propagation)."""
        X, y, _ = problem
        solver = ASGD(
            X, y, small_cfg(num_iterations=500, run_timeout_s=30), devices=devices8
        )
        calls = {"n": 0}
        orig = solver._step

        def flaky_step(Xs, ys, w, key):
            calls["n"] += 1
            if calls["n"] > 20:
                raise RuntimeError("injected device failure")
            return orig(Xs, ys, w, key)

        solver._step = flaky_step
        with pytest.raises(RuntimeError):
            solver.run()

    def test_straggler_injection_slows_worker0(self, devices8, problem):
        X, y, _ = problem
        cfg = small_cfg(
            num_iterations=200, coeff=3.0, calibration_iters=40, printer_freq=1000
        )
        res = ASGD(X, y, cfg, devices=devices8).run()
        assert res.avg_delay_ms > 0  # calibration happened
        assert res.accepted == 200


class TestASGDSync:
    def test_sync_converges(self, devices8, problem):
        X, y, _ = problem
        res = ASGD(
            X, y, small_cfg(num_iterations=60, gamma=2.0), devices=devices8
        ).run_sync()
        assert res.rounds == 60
        assert res.trajectory[-1][1] < res.trajectory[0][1] * 0.2
        assert res.max_staleness <= 8  # full drain keeps staleness ~= nw

    def test_sync_deterministic(self, devices8, problem):
        X, y, _ = problem
        cfg = small_cfg(num_iterations=20, gamma=1.0, coeff=0.0)
        r1 = ASGD(X, y, cfg, devices=devices8).run_sync()
        r2 = ASGD(X, y, cfg, devices=devices8).run_sync()
        np.testing.assert_allclose(r1.final_w, r2.final_w, rtol=1e-5)


class TestASAGA:
    def test_async_converges(self, devices8, problem):
        X, y, _ = problem
        cfg = small_cfg(num_iterations=800, gamma=0.02, batch_rate=0.2)
        res = ASAGA(X, y, cfg, devices=devices8).run()
        assert res.accepted == 800
        # threshold calibrated with the pre-run compile warm-up in place:
        # with no compile serialization of early rounds, dispatch runs at
        # full speed (and full staleness) from round 0, which costs a few
        # percent of per-update progress -- the async tradeoff under test
        assert res.trajectory[-1][1] < res.trajectory[0][1] * 0.4

    def test_sync_converges(self, devices8, problem):
        X, y, _ = problem
        cfg = small_cfg(num_iterations=60, gamma=0.5)
        res = ASAGA(X, y, cfg, devices=devices8).run_sync()
        assert res.rounds == 60
        assert res.trajectory[-1][1] < res.trajectory[0][1] * 0.5

    def test_rejects_non_least_squares(self, problem):
        X, y, _ = problem
        with pytest.raises(ValueError, match="least_squares"):
            ASAGA(X, y, small_cfg(loss="logistic"))

    def test_alpha_bar_tracks_table_mean_exactly(self, devices8, problem):
        """The invariant our commit protocol guarantees (and the reference's
        does not, under dispatch overlap): alpha_bar == (1/N) sum_i
        alpha_i * x_i at all times -- checked after a heavily-overlapped run."""
        X, y, _ = problem
        res = ASAGA(
            X, y, small_cfg(num_iterations=500, gamma=0.02, batch_rate=0.2,
                            bucket_ratio=0.25),
            devices=devices8,
        ).run()
        n = X.shape[0]
        expected = np.zeros(X.shape[1], np.float64)
        for wid, alpha_slice in res.extras["alpha"].items():
            lo = wid * (n // 8)
            Xp = X[lo : lo + alpha_slice.shape[0]]
            expected += Xp.T.astype(np.float64) @ alpha_slice.astype(np.float64)
        expected /= n
        np.testing.assert_allclose(
            res.extras["alpha_bar"], expected, rtol=1e-3, atol=1e-4
        )


class TestMiniBatchSGD:
    def test_full_batch_matches_exact_gd(self, devices8, problem):
        X, y, _ = problem
        mesh = make_mesh(8, devices=devices8)
        sgd = MiniBatchSGD(gamma=2.0, batch_rate=1.0, num_iterations=5, seed=0)
        w, losses, snaps = sgd.run(X, y, mesh=mesh)
        # replicate by hand: full-batch GD with lr = gamma/sqrt(i+1)/n
        n = X.shape[0]
        wr = np.zeros(X.shape[1], np.float32)
        for i in range(5):
            g = X.T @ (X @ wr - y)
            wr = wr - 2.0 / np.sqrt(i + 1.0) * g / n
        np.testing.assert_allclose(w, wr, rtol=2e-3, atol=2e-4)

    def test_loss_history_decreasing(self, devices8, problem):
        X, y, _ = problem
        mesh = make_mesh(8, devices=devices8)
        sgd = MiniBatchSGD(gamma=1.0, batch_rate=0.5, num_iterations=40)
        _, losses, _ = sgd.run(X, y, mesh=mesh)
        assert losses[-1] < losses[0]
        assert len(losses) == 40

    def test_padding_rows_do_not_change_result(self, devices8):
        # n=1000 not divisible by 8 -> 24 pad rows; count must exclude them
        X, y, _ = make_regression(1000, 8, seed=9)
        mesh = make_mesh(8, devices=devices8)
        sgd = MiniBatchSGD(gamma=1.0, batch_rate=1.0, num_iterations=3, seed=1)
        w, _, _ = sgd.run(X, y, mesh=mesh)
        n = X.shape[0]
        wr = np.zeros(8, np.float32)
        for i in range(3):
            g = X.T @ (X @ wr - y)
            wr = wr - 1.0 / np.sqrt(i + 1.0) * g / n
        np.testing.assert_allclose(w, wr, rtol=2e-3, atol=2e-4)

    def test_l2_updater(self, devices8, problem):
        X, y, _ = problem
        mesh = make_mesh(8, devices=devices8)
        sgd = MiniBatchSGD(
            gamma=1.0, batch_rate=1.0, num_iterations=10, updater="l2",
            reg_param=0.1,
        )
        w, losses, _ = sgd.run(X, y, mesh=mesh)
        # L2 shrinks weights vs simple
        w_simple, _, _ = MiniBatchSGD(
            gamma=1.0, batch_rate=1.0, num_iterations=10
        ).run(X, y, mesh=mesh)
        assert np.linalg.norm(w) < np.linalg.norm(w_simple)

    def test_l1_updater_sparsifies(self, devices8, problem):
        X, y, _ = problem
        mesh = make_mesh(8, devices=devices8)
        w, _, _ = MiniBatchSGD(
            gamma=1.0, batch_rate=1.0, num_iterations=20, updater="l1",
            reg_param=0.5,
        ).run(X, y, mesh=mesh)
        assert np.mean(np.abs(w) < 1e-6) > 0.1  # some exact zeros

    def test_snapshots_warray_parity(self, devices8, problem):
        X, y, _ = problem
        mesh = make_mesh(8, devices=devices8)
        sgd = MiniBatchSGD(
            gamma=1.0, batch_rate=0.5, num_iterations=25, snapshot_every=10
        )
        _, _, snaps = sgd.run(X, y, mesh=mesh)
        assert [s[0] for s in snaps] == [0, 10, 20]

    def test_convergence_tol_stops_early(self, devices8, problem):
        X, y, _ = problem
        mesh = make_mesh(8, devices=devices8)
        sgd = MiniBatchSGD(
            gamma=0.01, batch_rate=1.0, num_iterations=100, convergence_tol=0.5
        )
        _, losses, _ = sgd.run(X, y, mesh=mesh)
        assert len(losses) < 100


class TestMiniBatchSGD2D:
    """2-D (dp, md) mesh: features shard over md, rows over dp; results
    must match the dp-only layout bit-for-bit up to float association."""

    @pytest.mark.parametrize("updater,reg", [
        ("simple", 0.0), ("l2", 0.01), ("l1", 0.001),
    ])
    def test_md_sharding_matches_dp_only(self, devices8, problem, updater, reg):
        from asyncframework_tpu.parallel import make_mesh

        X, y, _ = problem
        mk = lambda: MiniBatchSGD(
            gamma=0.5, batch_rate=0.5, num_iterations=40, seed=1,
            updater=updater, reg_param=reg,
        )
        m1 = make_mesh(4, axis_names=("dp", "md"), axis_sizes=(4, 1),
                       devices=devices8[:4])
        m2 = make_mesh(8, axis_names=("dp", "md"), axis_sizes=(4, 2),
                       devices=devices8)
        w1, l1, _ = mk().run(X, y, mesh=m1)
        w2, l2, _ = mk().run(X, y, mesh=m2)
        np.testing.assert_allclose(w1, w2, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-6)

    def test_md_sharding_with_feature_padding(self, devices8):
        """d not divisible by md: padded feature columns must not leak."""
        from asyncframework_tpu.parallel import make_mesh

        rs = np.random.default_rng(3)
        n, d = 256, 13  # 13 % 4 != 0
        X = rs.normal(size=(n, d)).astype(np.float32)
        w_true = rs.normal(size=(d,)).astype(np.float32)
        y = (X @ w_true).astype(np.float32)
        mesh = make_mesh(8, axis_names=("dp", "md"), axis_sizes=(2, 4),
                         devices=devices8)
        w, losses, _ = MiniBatchSGD(
            gamma=0.5, batch_rate=1.0, num_iterations=150, seed=0
        ).run(X, y, mesh=mesh)
        assert w.shape == (d,)
        assert losses[-1] < 0.05 * losses[0]


class TestBF16AndFlops:
    """bf16-with-f32-accumulate data path + counted-flops instrumentation."""

    @pytest.mark.slow
    def test_bf16_dataset_converges(self, devices8):
        from asyncframework_tpu.data.sharded import ShardedDataset

        ds = ShardedDataset.generate_on_device(
            4096, 32, 8, devices=devices8, seed=5, dtype=jnp.bfloat16
        )
        assert ds.shard(0).X.dtype == jnp.bfloat16
        assert ds.shard(0).y.dtype == jnp.float32
        res = ASGD(ds, None, small_cfg(gamma=2.0), devices=devices8).run()
        first, last = res.trajectory[0][1], res.trajectory[-1][1]
        assert last < first * 0.1, res.trajectory
        assert np.isfinite(res.final_w).all()

    def test_bf16_grad_matches_f32_within_tolerance(self, devices8):
        from asyncframework_tpu.ops.gradients import least_squares_grad_sum

        rs = np.random.default_rng(0)
        X = rs.normal(size=(256, 16)).astype(np.float32) / 4.0
        w = rs.normal(size=(16,)).astype(np.float32)
        y = rs.normal(size=(256,)).astype(np.float32)
        mask = (rs.random(256) < 0.5).astype(np.float32)
        g32 = np.asarray(least_squares_grad_sum(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w), jnp.asarray(mask)
        ))
        g16 = np.asarray(least_squares_grad_sum(
            jnp.asarray(X, jnp.bfloat16), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(mask),
        ))
        assert g16.dtype == np.float32  # f32 accumulate
        np.testing.assert_allclose(g16, g32, rtol=0.05, atol=0.5)

    def test_host_array_dtype_cast(self, devices8, problem):
        from asyncframework_tpu.data.sharded import ShardedDataset

        X, y, _ = problem
        ds = ShardedDataset(X, y, 8, devices=devices8, dtype=jnp.bfloat16)
        assert all(ds.shard(w).X.dtype == jnp.bfloat16 for w in range(8))

    def test_flops_counted_async(self, devices8, problem):
        from asyncframework_tpu.ops.steps import sparse_step_capacity
        from asyncframework_tpu.utils import flops as fl

        X, y, _ = problem
        cfg = small_cfg(num_iterations=50)
        res = ASGD(X, y, cfg, devices=devices8).run()
        # b=0.3 <= 0.5: the step compacts sampled rows, so the flop model
        # counts the static capacity, not the full shard
        cap = sparse_step_capacity(cfg.batch_rate, X.shape[0] // 8)
        per_task = fl.dense_task_flops(cap, X.shape[1])
        # every merged gradient (accepted or dropped) was computed
        assert res.total_flops >= (res.accepted + res.dropped) * per_task
        # and no more than the number of submitted rounds could produce
        assert res.total_flops <= res.rounds * 8 * per_task * 1.01 + per_task

    def test_flops_counted_sync(self, devices8, problem):
        from asyncframework_tpu.ops.steps import sparse_step_capacity
        from asyncframework_tpu.utils import flops as fl

        X, y, _ = problem
        cfg = small_cfg(num_iterations=20)
        res = ASGD(X, y, cfg, devices=devices8).run_sync()
        cap = sparse_step_capacity(cfg.batch_rate, X.shape[0] // 8)
        per_task = fl.dense_task_flops(cap, X.shape[1])
        assert res.total_flops == pytest.approx(20 * 8 * per_task, rel=0.01)

    def test_chip_peak_lookup(self):
        from asyncframework_tpu.utils.flops import chip_peak_flops, mfu

        class FakeTPU:
            platform = "tpu"
            device_kind = "TPU v5 lite"

        class FakeCPU:
            platform = "cpu"
            device_kind = "cpu"

        assert chip_peak_flops(FakeTPU()) == 197e12
        assert chip_peak_flops(FakeCPU()) is None
        assert mfu(197e12, 1.0, FakeTPU()) == pytest.approx(1.0)
        assert mfu(1e9, 1.0, FakeCPU()) is None
