"""Adaptive asynchrony controller (ISSUE 15).

The correctness spine:

- the delay-adaptive damping law is EXACT and per-item: monotone
  non-increasing in staleness, bounded in [floor, 1], free slack before
  it engages, and the damped merge kernel is bit-identical to the
  damped serial kernel at every factor (1.0 included, where both match
  the legacy undamped kernel bit for bit) -- so dedup/replay semantics
  are untouched;
- decisions are guarded: hysteresis dead-band, per-knob cooldown, and
  an oscillation guard that freezes a flapping knob; the cohort never
  actuates below its declared floor, pipeline depth never exceeds the
  configured depth, the merge budget never exceeds the compiled bound;
- CTRL propagation is monotone and fence-stamped: WELCOME/PULL deliver
  it to workers (re-delivered only while the ``cs`` stamp lags), SETMAP
  carries it to shard members, and a stale (ep, seq) install is refused
  -- decisions survive relaunches and promotions;
- ``async.control.enabled=0`` is byte- and step-identical to the knob
  being absent (per-op frame-byte totals under a fixed seed);
- THE acceptance (`ctrl` marker, rides every bin/chaos_sweep.py seed):
  a real heterogeneous cluster -- 3-shard group with warm standbys, two
  worker processes, one DELAY-injected straggler, the wan net profile
  when the sweep asks for it -- converges WITHOUT hand-tuning under the
  controller, decisions are recorded, and exactly-once + fencing
  invariants hold across a mid-run shard promotion.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu import conf as conf_mod
from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.metrics.top import (
    render_control,
    render_fleet,
    render_status,
)
from asyncframework_tpu.net import faults, frame, reset_net_totals
from asyncframework_tpu.net.retry import reset_breakers
from asyncframework_tpu.parallel import controller as ctrl_mod
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel import shardgroup as sg
from asyncframework_tpu.parallel.controller import (
    CONTROLLER_TUNABLES,
    AsyncController,
    ControlSink,
    ctrl_seq,
)
from asyncframework_tpu.solvers import SolverConfig
from asyncframework_tpu.utils.clock import ManualClock

pytestmark = pytest.mark.ctrl

CHILD = Path(__file__).parent / "ps_dcn_child.py"
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


def make_cfg(**kw):
    defaults = dict(
        num_workers=4, num_iterations=60, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.5, printer_freq=20, seed=42,
        calibration_iters=10**9, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    reset_net_totals()
    reset_breakers()
    ctrl_mod.reset_control_totals()
    set_global_conf(AsyncConf())
    yield
    reset_net_totals()
    reset_breakers()
    ctrl_mod.reset_control_totals()
    set_global_conf(None)


class FakePS:
    """Controller test double: just the surface AsyncController reads
    (also imported by bin/chaos_sweep.py's per-seed controller_sanity)."""

    def __init__(self, num_workers=8, bucket_ratio=1.0, pipeline_depth=0,
                 merge_max=8, epoch=0):
        self.cfg = make_cfg(num_workers=num_workers,
                            bucket_ratio=bucket_ratio,
                            pipeline_depth=pipeline_depth)
        self._merge_max = merge_max
        self.epoch = epoch
        self.wstats = {}
        self.signals = {"queue_depth": 0.0, "accepted": 0.0,
                        "done": 0.0}
        self.installed = []

    def worker_stats(self):
        return {w: dict(st) for w, st in self.wstats.items()}

    def control_signals(self):
        return dict(self.signals)

    def set_control(self, wire):
        self.installed.append(dict(wire))
        return True


def manual_controller(ps, **kw):
    clk = ManualClock()
    ctl = AsyncController(ps, conf=AsyncConf(),
                          now_fn=lambda: clk.now_ms() / 1e3, **kw)
    return ctl, clk


def steady_stats(nw=8, iv=10.0):
    return {str(w): {"accepted": 50, "interval_ms": iv}
            for w in range(nw)}


# ------------------------------------------------------------ damping law
class TestDampLaw:
    def _ps(self, algo="asgd"):
        import jax

        cfg = make_cfg(num_workers=4)
        return ps_dcn.ParameterServer(cfg, 8, 64,
                                      device=jax.devices()[0], port=0,
                                      algo=algo)

    def test_monotone_bounded_with_free_slack(self):
        ps = self._ps()
        try:
            assert ps._item_damp(0, 10**6) == 1.0  # control off: exact
            ps.set_control({"seq": 1, "ep": 0,
                            "damp": [1.0, 0.1, 4.0]})
            # within the free slack: exactly 1.0 (undamped, bit-exact)
            for tau in (0, 1, 4):
                assert ps._item_damp(0, tau) == 1.0
            vals = [ps._item_damp(0, tau) for tau in range(5, 200)]
            assert all(v2 <= v1 for v1, v2 in zip(vals, vals[1:]))
            assert all(0.1 <= v < 1.0 for v in vals)
            # deep staleness hits the floor, never below
            assert ps._item_damp(0, 10**6) == 0.1
            # the 1/(1+tau-free) family, exactly
            assert ps._item_damp(0, 6) == pytest.approx(1.0 / 3.0)
        finally:
            ps.stop()

    def test_wdamp_scales_and_floors(self):
        ps = self._ps()
        try:
            ps.set_control({"seq": 1, "ep": 0,
                            "damp": [1.0, 0.2, 100.0],
                            "wdamp": {"2": 0.5}})
            assert ps._item_damp(0, 0) == 1.0      # not in the table
            assert ps._item_damp(2, 0) == 0.5      # extra per-worker damp
            ps.set_control({"seq": 2, "ep": 0,
                            "damp": [1.0, 0.2, 100.0],
                            "wdamp": {"2": 0.01}})
            assert ps._item_damp(2, 0) == 0.2      # floored
        finally:
            ps.stop()

    def test_asaga_excluded_from_damping(self):
        ps = self._ps(algo="asaga")
        try:
            ps.set_control({"seq": 1, "ep": 0, "damp": [1.0, 0.1, 0.0]})
            assert ps._ctrl_damp is None
            assert ps._item_damp(0, 10**6) == 1.0
        finally:
            ps.stop()

    def test_never_exactly_zero(self):
        ps = self._ps()
        try:
            # adversarial wire: floor 0 -- an accepted item's factor must
            # stay strictly positive (the kernel keep bit is mask > 0)
            ps.set_control({"seq": 1, "ep": 0, "damp": [1.0, 0.0, 0.0],
                            "wdamp": {"0": 0.0}})
            assert ps._item_damp(0, 10**9) > 0.0
        finally:
            ps.stop()


class TestKernelExactness:
    D, M = 16, 4

    def _mats(self, seed):
        rng = np.random.default_rng(seed)
        w0 = rng.standard_normal(self.D).astype(np.float32)
        G = rng.standard_normal((self.M, self.D)).astype(np.float32)
        return w0, G

    def test_damped_merge_bit_identical_to_damped_serial(self):
        import jax
        import jax.numpy as jnp

        from asyncframework_tpu.ops import steps

        w0, G = self._mats(CHAOS_SEED)
        damps = np.array([1.0, 0.37, 0.1, 0.85], np.float32)
        merge = steps.make_asgd_apply_merge(1.2, 0.3, 64, 4)
        serial = steps.make_asgd_apply_damped(1.2, 0.3, 64, 4)
        wm, km = merge(jnp.asarray(w0), jnp.asarray(G),
                       jnp.asarray(damps), jnp.float32(0.0))
        ws, ks = jnp.asarray(w0), jnp.float32(0.0)
        for j in range(self.M):
            ws, ks = serial(ws, jnp.asarray(G[j]), ks,
                            np.float32(damps[j]))
        assert np.asarray(wm).tobytes() == np.asarray(ws).tobytes()
        assert float(km) == float(ks) == 4.0

    def test_damp_one_bit_identical_to_legacy_kernel(self):
        import jax.numpy as jnp

        from asyncframework_tpu.ops import steps

        w0, G = self._mats(CHAOS_SEED + 1)
        merge = steps.make_asgd_apply_merge(1.2, 0.3, 64, 4)
        legacy = steps.make_asgd_apply(1.2, 0.3, 64, 4)
        wm, _ = merge(jnp.asarray(w0), jnp.asarray(G),
                      jnp.ones(self.M, jnp.float32), jnp.float32(0.0))
        wl, kl = jnp.asarray(w0), jnp.float32(0.0)
        for j in range(self.M):
            wl, kl = legacy(wl, jnp.asarray(G[j]), kl)
        assert np.asarray(wm).tobytes() == np.asarray(wl).tobytes()


# -------------------------------------------------------- decision units
class TestDecisionUnits:
    def test_b_drops_per_straggler(self):
        ps = FakePS(num_workers=8, bucket_ratio=1.0)  # conf b = 8
        ctl, clk = manual_controller(ps)
        stats = steady_stats()
        stats["3"]["interval_ms"] = 500.0
        stats["5"]["interval_ms"] = 400.0
        for _ in range(4):
            clk.advance(3000)
            ps.wstats = stats
            ctl.tick()
        assert ctl.status()["knobs"]["b"]["value"] == 6  # 8 - 2 flagged

    def test_b_never_below_declared_floor(self):
        class AllFlagged:
            def derived(self):
                return {}

            def stragglers(self):
                return {str(w): {"score": 9.0, "flagged": True}
                        for w in range(8)}

        ps = FakePS(num_workers=8, bucket_ratio=1.0)
        ctl, clk = manual_controller(ps, observer=AllFlagged())
        floor = max(1, ctl._bounds["async.bucket.ratio"][0] * 8)
        before = ctrl_mod.control_totals()["clamps"]
        for _ in range(8):
            clk.advance(3000)
            ps.wstats = steady_stats()
            ctl.tick()
        # every worker flagged: the raw target (0) is clamped at the
        # declared floor, never below
        assert ctl.status()["knobs"]["b"]["value"] == floor
        assert ctrl_mod.control_totals()["clamps"] > before

    def test_two_worker_cohort_still_flags(self):
        # peer-median-excluding-self: the observer's stance, so a
        # 2-worker cohort can flag its 10x member
        ps = FakePS(num_workers=2, bucket_ratio=1.0)
        ctl, clk = manual_controller(ps)
        stats = steady_stats(nw=2)
        stats["1"]["interval_ms"] = 500.0
        for _ in range(4):
            clk.advance(3000)
            ps.wstats = stats
            ctl.tick()
        assert ctl.status()["knobs"]["b"]["value"] == 1

    def test_b_restores_when_spread_closes(self):
        ps = FakePS(num_workers=8, bucket_ratio=1.0)
        ctl, clk = manual_controller(ps)
        slow = steady_stats()
        slow["3"]["interval_ms"] = 400.0
        for _ in range(4):
            clk.advance(3000)
            ps.wstats = slow
            ctl.tick()
        assert ctl.status()["knobs"]["b"]["value"] == 7
        for _ in range(4):
            clk.advance(3000)
            ps.wstats = steady_stats()
            ctl.tick()
        assert ctl.status()["knobs"]["b"]["value"] == 8

    def test_hysteresis_blocks_sub_step_changes(self):
        ps = FakePS()
        ctl, clk = manual_controller(ps)
        knob = ctl._knobs["merge"]
        # within the dead-band (< max(1, 25%)): no actuation
        got = ctl._actuate("async.push.merge", knob, knob.value + 0.5,
                           clk.now_ms() / 1e3, "test", 1.0, 64.0)
        assert got == [] and knob.changes == 0

    def test_cooldown_blocks_rapid_changes(self):
        ps = FakePS()
        ctl, clk = manual_controller(ps)
        knob = ctl._knobs["merge"]
        now = lambda: clk.now_ms() / 1e3  # noqa: E731
        assert ctl._actuate("async.push.merge", knob, 4.0, now(),
                            "t", 1.0, 64.0)
        clk.advance(500)  # < cooldown 2s
        assert ctl._actuate("async.push.merge", knob, 16.0, now(),
                            "t", 1.0, 64.0) == []
        clk.advance(5000)
        assert ctl._actuate("async.push.merge", knob, 16.0, now(),
                            "t", 1.0, 64.0)

    def test_oscillation_guard_trips_and_freezes(self):
        ps = FakePS()
        ctl, clk = manual_controller(ps)
        knob = ctl._knobs["merge"]
        now = lambda: clk.now_ms() / 1e3  # noqa: E731
        before = ctrl_mod.control_totals()["osc_trips"]
        targets = [2.0, 8.0, 2.0, 8.0, 2.0, 8.0]
        for t in targets:
            clk.advance(3000)
            ctl._actuate("async.push.merge", knob, t, now(), "flap",
                         1.0, 64.0)
        assert ctrl_mod.control_totals()["osc_trips"] > before
        assert ctl.status()["knobs"]["merge"]["frozen"] is True
        frozen_at = knob.value
        clk.advance(2000)  # still inside the freeze window
        ctl._actuate("async.push.merge", knob, frozen_at + 30, now(),
                     "t", 1.0, 64.0)
        assert knob.value == frozen_at
        clk.advance(60_000)  # freeze expires, history cleared
        assert ctl._actuate("async.push.merge", knob, frozen_at + 30,
                            now(), "t", 1.0, 64.0)

    def test_depth_sized_from_rtt_vs_compute_and_capped(self):
        ps = FakePS(pipeline_depth=4)
        ctl, clk = manual_controller(ps)
        stats = steady_stats()
        for st in stats.values():
            st["rtt_ms"], st["compute_ms"] = 20.0, 10.0
        for _ in range(3):
            clk.advance(3000)
            ps.wstats = stats
            ctl.tick()
        # 1 + 20/10 = 3, within [1, configured 4]
        assert ctl.status()["knobs"]["depth"]["value"] == 3
        for st in stats.values():
            st["rtt_ms"] = 500.0  # formula says 51 -- cap at configured
        for _ in range(3):
            clk.advance(3000)
            ps.wstats = stats
            ctl.tick()
        assert ctl.status()["knobs"]["depth"]["value"] == 4
        assert ctrl_mod.control_totals()["clamps"] >= 1

    def test_depth_untouched_on_serial_loops(self):
        ps = FakePS(pipeline_depth=0)
        ctl, clk = manual_controller(ps)
        stats = steady_stats()
        for st in stats.values():
            st["rtt_ms"], st["compute_ms"] = 50.0, 1.0
        clk.advance(3000)
        ps.wstats = stats
        ctl.tick()
        assert ctl.status()["knobs"]["depth"]["value"] == 0
        assert ctl.ctrl_wire()["depth"] == 0

    def test_merge_budget_tracks_queue_pressure(self):
        ps = FakePS(merge_max=8)
        ctl, clk = manual_controller(ps)
        ps.signals["queue_depth"] = 20.0
        # budget starts at the conf 8 (= compiled bound): pressure can
        # never grow it past the bound
        for _ in range(4):
            clk.advance(3000)
            ctl.tick()
        assert ctl.status()["knobs"]["merge"]["value"] == 8
        ps.signals["queue_depth"] = 0.0
        for _ in range(14):  # the queue EWMA must decay below the
            clk.advance(3000)  # shrink threshold (0.125 * budget) first
            ctl.tick()
        assert ctl.status()["knobs"]["merge"]["value"] < 8

    def test_supervisor_suspects_count_as_stragglers(self):
        from asyncframework_tpu.parallel import supervisor as sup_mod

        class FakeSup:
            def membership(self):
                return {2: {"state": sup_mod.SUSPECT},
                        3: {"state": "live"}}

        ps = FakePS(num_workers=8, bucket_ratio=1.0)
        ps.supervisor = FakeSup()
        ctl, clk = manual_controller(ps)
        for _ in range(4):
            clk.advance(3000)
            ps.wstats = steady_stats()  # intervals all even: only the
            ctl.tick()                  # SUSPECT membership flags w2
        assert ctl.status()["knobs"]["b"]["value"] == 7

    def test_wdamp_follows_observer_straggler_flags(self):
        class FakeObserver:
            table = {}

            def derived(self):
                return {}

            def stragglers(self):
                return dict(self.table)

        obs = FakeObserver()
        ps = FakePS()
        ctl, clk = manual_controller(ps, observer=obs)
        obs.table = {"5": {"score": 4.0, "flagged": True},
                     "1": {"score": 1.1, "flagged": False}}
        clk.advance(3000)
        ctl.tick()
        wire = ctl.ctrl_wire()
        assert wire["wdamp"] == {"5": 0.25}
        obs.table = {}
        clk.advance(1000)  # inside the cooldown: the clear must WAIT
        ctl.tick()         # (wdamp rides the same guards as the knobs)
        assert ctl.ctrl_wire()["wdamp"] == {"5": 0.25}
        clk.advance(3000)
        ctl.tick()
        assert "wdamp" not in ctl.ctrl_wire()
        assert ctrl_mod.control_totals()["wdamp_set"] == 2

    def test_actuating_undeclared_key_raises(self):
        ps = FakePS()
        ctl, clk = manual_controller(ps)
        with pytest.raises(ValueError, match="undeclared tunable"):
            ctl._actuate("async.pull.mode", ctl._knobs["merge"], 2.0,
                         0.0, "t", 1.0, 8.0)

    def test_wire_seq_monotone_and_fence_stamped(self):
        ps = FakePS(epoch=3)
        ctl, _clk = manual_controller(ps)
        ctl._install("r1")
        ctl._install("r2")
        w1, w2 = ps.installed[-2:]
        assert w2["seq"] == w1["seq"] + 1
        assert w1["ep"] == 3
        assert ctrl_seq(w2) > ctrl_seq(w1)


# ----------------------------------------------------- CTRL propagation
class TestCtrlPropagation:
    def test_sink_monotone_install_and_depth_clamp(self):
        sink = ControlSink({"seq": 4, "ep": 1, "depth": 3})
        assert sink.seq == 4
        assert sink.depth(configured=8) == 3
        assert sink.depth(configured=2) == 2      # never past configured
        assert not sink.install({"seq": 3, "ep": 1, "depth": 9})
        assert sink.depth(configured=8) == 3      # stale install refused
        assert sink.install({"seq": 1, "ep": 2, "depth": 9})  # newer ep
        assert sink.depth(configured=8) == 8
        sink2 = ControlSink({"seq": 1, "ep": 0})
        assert sink2.depth(configured=5) == 5     # 0/absent = configured

    def test_ps_install_is_monotone_and_fence_stamped(self):
        import jax

        ps = ps_dcn.ParameterServer(make_cfg(), 8, 64,
                                    device=jax.devices()[0], port=0)
        try:
            assert ps.set_control({"seq": 2, "ep": 1, "b": 2})
            assert not ps.set_control({"seq": 1, "ep": 1, "b": 3})
            # a deposed controller's stamp (older epoch) is refused even
            # at a higher seq -- promotion safety for decisions
            assert not ps.set_control({"seq": 9, "ep": 0, "b": 3})
            assert ps.ctrl["b"] == 2 and ps.ctrl_stale_rejects == 2
            assert ps.set_control({"seq": 1, "ep": 2, "b": 4})
            assert ps._ctrl_b == 4
        finally:
            ps.stop()

    def test_cohort_threshold_uses_ctrl_b(self):
        import jax

        ps = ps_dcn.ParameterServer(make_cfg(num_workers=8,
                                             bucket_ratio=1.0),
                                    8, 64, device=jax.devices()[0],
                                    port=0)
        try:
            assert ps._cohort_threshold() == 8
            ps.set_control({"seq": 1, "ep": 0, "b": 3})
            assert ps._cohort_threshold() == 3
            ps.set_control({"seq": 2, "ep": 0, "b": 0})  # override off
            assert ps._cohort_threshold() == 8
        finally:
            ps.stop()

    def test_welcome_and_pull_deliver_then_stop_redelivering(self):
        import jax

        cfg = make_cfg(num_workers=1, bucket_ratio=0.0)
        ps = ps_dcn.ParameterServer(cfg, 8, 64,
                                    device=jax.devices()[0],
                                    port=0).start()
        cl = None
        try:
            ps.set_control({"seq": 5, "ep": 0, "b": 1,
                            "damp": [1.0, 0.1, 1.0]})
            hello_cl = ps_dcn.PSClient("127.0.0.1", ps.port)
            welcome = hello_cl.hello("t-proc", [0], pid=os.getpid())
            hello_cl.bye()
            assert welcome["ctrl"]["seq"] == 5  # WELCOME carries CTRL
            sink = ControlSink(welcome["ctrl"])
            installs = []
            orig = sink.install
            sink.install = lambda w: installs.append(w) or orig(w)
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, ctrl_sink=sink)
            got = cl.pull(0)
            assert got is not None
            # the request's cs stamp (5) is current: NOT re-delivered
            assert installs == []
            ps.set_control({"seq": 6, "ep": 0, "b": 1,
                            "damp": [1.0, 0.1, 1.0]})
            got = cl.pull(0)
            assert got is not None
            assert [w["seq"] for w in installs] == [6]
            assert sink.seq == 6
            got = cl.pull(0)  # acked: no third delivery
            assert [w["seq"] for w in installs] == [6]
        finally:
            if cl is not None:
                cl.bye()
            ps.stop()

    def test_restarted_controller_epoch_redelivers_over_pull(self):
        """A relaunched controller under a minted HIGHER epoch restarts
        seq near 1: the PULL re-delivery gate must compare the full
        (epoch, seq) stamp -- a bare-seq compare would strand every
        surviving worker on the deposed controller's decisions."""
        import jax

        cfg = make_cfg(num_workers=1, bucket_ratio=0.0)
        ps = ps_dcn.ParameterServer(cfg, 8, 64,
                                    device=jax.devices()[0],
                                    port=0).start()
        cl = None
        try:
            ps.set_control({"seq": 57, "ep": 1, "b": 1})
            sink = ControlSink({"seq": 57, "ep": 1, "b": 1})
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, ctrl_sink=sink)
            # the restarted controller's first decision: higher epoch,
            # tiny seq
            assert ps.set_control({"seq": 1, "ep": 2, "b": 1})
            assert cl.pull(0) is not None
            assert sink.wire()["ep"] == 2 and sink.seq == 1
        finally:
            if cl is not None:
                cl.bye()
            ps.stop()

    def test_setmap_carries_ctrl_and_stale_refused(self):
        import jax

        ps = ps_dcn.ParameterServer(make_cfg(), 8, 64,
                                    device=jax.devices()[0],
                                    port=0).start()
        try:
            wire_map = [["127.0.0.1", ps.port, 0, 8]]
            sg._oneshot("127.0.0.1", ps.port,
                        {"op": "SETMAP", "index": 0, "shards": wire_map,
                         "ctrl": {"seq": 3, "ep": 0, "merge": 2}},
                        timeout_s=5.0)
            assert ps.ctrl["seq"] == 3 and ps._ctrl_merge == 2
            # SHARDMAP advertises the installed ctrl (observability +
            # promotion-following clients)
            hdr = sg._oneshot("127.0.0.1", ps.port, {"op": "SHARDMAP"},
                              timeout_s=5.0)
            assert hdr["ctrl"]["seq"] == 3
            sg._oneshot("127.0.0.1", ps.port,
                        {"op": "SETMAP", "index": 0, "shards": wire_map,
                         "ctrl": {"seq": 1, "ep": 0, "merge": 7}},
                        timeout_s=5.0)
            assert ps.ctrl["seq"] == 3 and ps._ctrl_merge == 2
        finally:
            ps.stop()

    def test_damped_pushes_mirror_to_standby_exactly(self):
        """The replication stream ships each item's damp factor: a hot
        standby must apply EXACTLY the step the primary did, or its
        model silently diverges and a promotion serves the divergent
        copy (the regression class PR 13's _k_dev fix closed)."""
        set_global_conf(AsyncConf({"async.fence.enabled": True}))
        cfg = make_cfg(num_workers=2, num_iterations=10**6,
                       bucket_ratio=0.0, printer_freq=10)
        prim = ps_dcn.ParameterServer(cfg, 8, 64, port=0).start()
        sb = ps_dcn.ParameterServer(cfg, 8, 64, port=0,
                                    standby=True).start()
        prim.attach_standby("127.0.0.1", sb.port)
        cl = None
        try:
            # free slack 0: every push at staleness >= 1 is damped
            prim.set_control({"seq": 1, "ep": prim.epoch or 0,
                              "damp": [1.0, 0.1, 0.0]})
            assert prim._ctrl_damp is not None
            cl = ps_dcn.PSClient("127.0.0.1", prim.port)
            rng = np.random.default_rng(CHAOS_SEED)
            ts0, _w, _a, _c = cl.pull(0)
            for _ in range(20):
                # re-push against the ORIGINAL basis: staleness climbs
                # 0,1,2,... so most applies run the damped kernel
                cl.push(0, ts0, rng.normal(size=8).astype(np.float32))
            assert prim.max_staleness >= 1  # damping definitely engaged
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if sb._clock >= prim._clock and prim.repl.synced:
                    break
                time.sleep(0.02)
            assert sb._clock == prim._clock
            np.testing.assert_array_equal(np.asarray(prim._w),
                                          np.asarray(sb._w))
            cl.bye()
            cl = None
        finally:
            if cl is not None:
                cl.bye()
            prim.stop()
            sb.stop()

    def test_equal_stamp_redelivery_not_counted_stale(self):
        import jax

        ps = ps_dcn.ParameterServer(make_cfg(), 8, 64,
                                    device=jax.devices()[0], port=0)
        try:
            wire = {"seq": 2, "ep": 1, "b": 2}
            assert ps.set_control(wire)
            # the group re-announces its stored ctrl on every SETMAP
            # sweep: an identical re-delivery is idempotent, NOT a
            # deposed-controller fence event
            assert not ps.set_control(dict(wire))
            assert ps.ctrl_stale_rejects == 0
            assert not ps.set_control({"seq": 1, "ep": 1, "b": 9})
            assert ps.ctrl_stale_rejects == 1
        finally:
            ps.stop()

    def test_drain_budget_resized_by_ctrl(self):
        import jax

        ps = ps_dcn.ParameterServer(make_cfg(num_workers=1,
                                             bucket_ratio=0.0),
                                    8, 64, device=jax.devices()[0],
                                    port=0)
        try:
            assert ps._merge_max == 8  # conf default = compiled bound
            ps.set_control({"seq": 1, "ep": 0, "merge": 2})
            assert ps._ctrl_merge == 2
            # a hostile/overshooting decision can never exceed the
            # compiled bound
            ps.set_control({"seq": 2, "ep": 0, "merge": 512})
            assert min(ps._ctrl_merge, ps._merge_max) == 8
        finally:
            ps.stop()


# --------------------------------------------------------- byte identity
class TestControlOffIsClassic:
    def test_enabled0_conf_set_matches_unset_byte_identical(self):
        """`async.control.enabled=0` must leave the wire byte-identical
        and the run step-identical to the knob being absent (the
        shards=1 / depth=0 / devices=0 discipline): per-op frame-byte
        totals must match EXACTLY under a fixed seed."""
        import jax

        from asyncframework_tpu.data.sharded import ShardedDataset

        results = []
        for control_conf in (None, "0"):
            conf = (AsyncConf().set("async.pull.mode", "full")
                    .set("async.trace.sample", 0.0))
            if control_conf is not None:
                conf.set("async.control.enabled", control_conf)
            set_global_conf(conf)
            reset_net_totals()
            cfg = make_cfg(num_workers=1, num_iterations=40,
                           bucket_ratio=0.0)
            dev = jax.devices()[0]
            ds = ShardedDataset.generate_on_device(
                512, 16, 1, devices=[dev], seed=11, noise=0.01)
            ps = ps_dcn.ParameterServer(cfg, 16, 512, device=dev,
                                        port=0).start()
            try:
                counts = ps_dcn.run_worker_process(
                    "127.0.0.1", ps.port, [0], {0: ds.shard(0)}, cfg,
                    16, 512, deadline_s=120.0)
                assert ps.wait_done(timeout_s=10.0)
            finally:
                ps.stop()
            results.append({
                "accepted": ps.accepted, "dropped": ps.dropped,
                "max_staleness": ps.max_staleness, "clock": ps._clock,
                "counts": dict(counts),
                "bytes": frame.bytes_totals(),
            })
        unset, off = results
        assert unset["accepted"] == off["accepted"] == 40
        assert unset == off, (unset, off)

    def test_control_off_ps_serves_no_ctrl_keys(self):
        import jax

        ps = ps_dcn.ParameterServer(make_cfg(num_workers=1,
                                             bucket_ratio=0.0),
                                    8, 64, device=jax.devices()[0],
                                    port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port)
            welcome = cl.hello("t", [0], pid=os.getpid())
            assert "ctrl" not in welcome
            hdr = sg._oneshot("127.0.0.1", ps.port, {"op": "SHARDMAP"},
                              timeout_s=5.0)
            assert "ctrl" not in hdr
            cl.bye()
        finally:
            ps.stop()


# -------------------------------------------------------------- surfaces
class TestSurfaces:
    def test_tunables_declared_with_bounds(self):
        reg = conf_mod.registry()
        for key in CONTROLLER_TUNABLES:
            entry = reg[key]
            assert entry.tunable is True
            assert entry.floor is not None and entry.ceiling is not None
            assert entry.floor < entry.ceiling

    def test_registry_has_control_family(self):
        from asyncframework_tpu.metrics import registry

        fams = registry.families()
        assert "control" in fams
        tot = fams["control"].totals()
        assert "changes" in tot and "osc_trips" in tot
        assert "control" in registry.series_families()

    def test_default_rules_include_controller_converged(self):
        from asyncframework_tpu.metrics.slo import parse_rules

        rules = {r.name: r for r in parse_rules(
            conf_mod.SLO_RULES.default)}
        rule = rules["controller_converged"]
        assert rule.series == "control.changes" and rule.agg == "rate"
        assert rule.unless_series == "observer.fleet_done"

    def test_render_control_pure_and_embedded(self):
        ps = FakePS()
        ctl, clk = manual_controller(ps)
        ps.signals["queue_depth"] = 0.0
        for _ in range(4):
            clk.advance(3000)
            ctl.tick()
        section = ctl.status()
        out = render_control(section, plain=True)
        assert "control: seq=" in out and "merge" in out
        assert "last:" in out  # the merge shrink decision + reason
        assert "FROZEN" not in out
        # embedded in the async-top role view ...
        framed = render_status({"role": "driver", "control": section})
        assert "control: seq=" in framed
        # ... and in the async-mon fleet view
        fleet = render_fleet({"roles": {}, "derived": {},
                              "control": {"role": "ps", **section}})
        assert "control: seq=" in fleet and "via=ps" in fleet

    def test_k8s_primary_shard_pod_enables_control(self):
        from asyncframework_tpu.deploy import k8s

        objs = k8s.render_ps_shards(3, 48, 1024)
        by_name = {o["metadata"]["name"]: o for o in objs
                   if o["kind"] == "Deployment"}

        def envs(dep):
            c = dep["spec"]["template"]["spec"]["containers"][0]
            return {e["name"]: e.get("value") for e in c["env"]}

        assert envs(by_name["async-ps-shard-0"]).get(
            "ASYNCTPU_ASYNC_CONTROL_ENABLED") == "1"
        # secondaries follow the primary's SETMAP fan-out, they do not
        # run their own control loop
        assert "ASYNCTPU_ASYNC_CONTROL_ENABLED" not in envs(
            by_name["async-ps-shard-1"])

    def test_ctrl_fanout_setmaps_other_map_entries(self):
        import jax

        primary = ps_dcn.ParameterServer(make_cfg(), 4, 64,
                                         device=jax.devices()[0],
                                         port=0)
        secondary = ps_dcn.ParameterServer(make_cfg(), 4, 64,
                                           device=jax.devices()[0],
                                           port=0).start()
        try:
            wire_map = [["127.0.0.1", 65000, 0, 4],
                        ["127.0.0.1", secondary.port, 4, 8]]
            primary.shard_map = wire_map
            primary.shard_index = 0
            fanout = sg.CtrlFanout(primary)
            fanout.install_ctrl({"seq": 2, "ep": 0, "b": 3})
            # the fan-out runs on the coalescing announcer thread (a
            # dark member must never stall the decision loop): poll
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and secondary.ctrl is None:
                time.sleep(0.02)
            fanout.stop()
            assert secondary.ctrl is not None
            assert secondary.ctrl["seq"] == 2 and secondary._ctrl_b == 3
        finally:
            secondary.stop()
            primary.stop()

    def test_controller_status_section_registered(self):
        import jax

        from asyncframework_tpu.metrics import live as live_mod

        ps = ps_dcn.ParameterServer(make_cfg(), 8, 64,
                                    device=jax.devices()[0], port=0)
        ctl = AsyncController(ps, conf=AsyncConf()).start()
        try:
            status = live_mod.process_status("driver")
            assert status["control"]["enabled"] is True
            assert status["control"]["seq"] >= 1
            assert ps.ctrl is not None  # start() installed the law
        finally:
            ctl.stop()
            ps.stop()
            status = live_mod.process_status("driver")
            assert "control" not in status


# ------------------------------------------------------------ acceptance
class TestWanDelayAcceptance:
    """Real processes end to end, the heterogeneous cluster the ISSUE
    names: a 3-shard group (in-process primary + 2 child shards with
    warm standbys), two worker processes -- one DELAY-injected -- under
    the controller, with the wan net profile merged in when the sweep
    exports ASYNC_CHAOS_NET_PROFILE.  Converges without hand-tuning,
    decisions recorded, exactly-once + fencing hold across a mid-run
    promotion."""

    NW, N, D = 8, 4096, 24
    ITERS = 900

    def _worker(self, port, wpid, tmp, wids, delay_ms=0.0):
        env = dict(os.environ)
        env.update({
            "PS_ROLE": "worker", "PS_PORT": str(port),
            "PS_WORKER_ID": str(wpid), "PS_NUM_WORKER_PROCS": "2",
            "PS_NUM_ITER": str(self.ITERS),
            "PS_WIDS": ",".join(str(w) for w in wids),
            "PS_EVAL": "1" if wpid == 0 else "1",
            "JAX_PLATFORMS": "cpu",
        })
        sched = faults.FaultSchedule()
        if delay_ms > 0:
            # the deterministic slow-but-alive member: every PUSH of
            # this child pays delay_ms (count=0 = forever)
            sched.add_delay("*", "PUSH", delay_ms, count=0)
        profile = faults.profile_schedule_from_env(CHAOS_SEED)
        if profile is not None:
            sched = faults.merge_schedules(sched, profile)
        if sched.events:
            env["ASYNCTPU_ASYNC_NET_FAULT_SCHEDULE"] = sched.to_json()
        return subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(tmp, f"worker{wpid}.stderr.log"),
                        "w"),
            text=True,
        )

    def test_controller_on_heterogeneous_cluster_with_promotion(
            self, tmp_path):
        import jax

        # cfg MUST mirror tests/ps_dcn_child.py::config()
        cfg = SolverConfig(
            num_workers=self.NW, num_iterations=self.ITERS, gamma=1.2,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
            printer_freq=50, seed=42, calibration_iters=20,
            run_timeout_s=120.0,
        )
        overlays = {"async.fence.enabled": True, "async.ps.standby": 1}
        conf = AsyncConf(dict(overlays))
        set_global_conf(conf)
        port0 = frame.free_port()
        group = sg.ShardGroup(
            cfg, self.D, self.N, 3, checkpoint_dir=str(tmp_path),
            indices=range(1, 3), fixed_entries={0: ("127.0.0.1", port0)},
            worker_procs=2, dead_after_s=1.0, check_interval_s=0.2,
            stderr_dir=str(tmp_path), conf_overlays=dict(overlays),
        ).start()
        from asyncframework_tpu.parallel.supervisor import (
            ElasticSupervisor,
        )

        sup = ElasticSupervisor(self.NW, dead_after_s=5.0,
                                check_interval_s=0.2)
        ps = ps_dcn.ParameterServer(
            cfg, sg.shard_ranges(self.D, 3)[0][1], self.N,
            port=port0, device=jax.devices()[0], supervisor=sup,
            shard_map=group.smap.to_wire(), shard_index=0,
            shard_epochs=group.epochs_wire(),
        ).start()
        ctl = AsyncController(ps, conf=conf, group=group).start()
        workers = []
        try:
            # heterogeneous by construction: child 1 (wids 6,7) pays
            # 150 ms per PUSH -- the deterministic DELAYed straggler
            workers = [
                self._worker(port0, 0, str(tmp_path),
                             wids=range(0, 6)),
                self._worker(port0, 1, str(tmp_path), wids=(6, 7),
                             delay_ms=150.0),
            ]
            # the controller detects the spread and re-clamps the wave
            # threshold below the configured b=4 -- one DELAYed worker
            # stops gating every wave
            deadline = time.monotonic() + 60.0
            b_seen = None
            while time.monotonic() < deadline:
                b_seen = ctl.status()["knobs"]["b"]["value"]
                if b_seen < 4:
                    break
                time.sleep(0.2)
            assert b_seen is not None and b_seen < 4, \
                f"controller never re-clamped b (still {b_seen})"
            floor = ctl._bounds["async.bucket.ratio"][0] * self.NW
            assert b_seen >= max(1, floor)
            # mid-run shard promotion: SIGKILL shard 1's primary once it
            # has applied a seeded threshold of merges
            kill_after = 60 + (CHAOS_SEED % 50)
            watch = ps_dcn.PSClient("127.0.0.1", group.port_of(1))
            wait_deadline = time.monotonic() + 60.0
            while time.monotonic() < wait_deadline:
                got = watch.subscribe(0)
                if got is not None and got[2] >= kill_after:
                    break
                time.sleep(0.02)
            try:
                watch.bye()
            except (ConnectionError, OSError):
                pass
            os.kill(group.pid_of(1), signal.SIGKILL)
            # run completes through the failover, no hand-tuned knobs
            assert ps.wait_done(timeout_s=120.0)
            group.finish()
            assert ps.accepted == self.ITERS
            assert set(ps.accepted_by_wid) == set(range(self.NW))
            # exactly-once at the primary: every clock tick is exactly
            # one accept-or-drop verdict
            assert ps.accepted + ps.dropped == ps._clock
            # fencing: the failover was a PROMOTION under a minted
            # epoch, not a restart-with-replay
            assert group.promotions_of(1) >= 1
            assert group.restarts_of(1) == 0
            # decisions were recorded -- counters, status, and the CTRL
            # payload that reached the wire
            totals = ctrl_mod.control_totals()
            assert totals["changes"] >= 1 and totals["ticks"] >= 1
            assert ctl.status()["last_decision"] is not None
            assert ps.ctrl["seq"] >= 2
            # ... and SURVIVED the promotion: the promoted member serves
            # the group's current ctrl
            hdr = sg._oneshot("127.0.0.1", group.port_of(1),
                              {"op": "SHARDMAP"}, timeout_s=5.0)
            assert hdr.get("ctrl"), "promoted member lost the CTRL state"
            assert hdr["ctrl"]["seq"] >= 1
            # the promoted member's own exactly-once accounting
            result1 = group.result_of(1, timeout_s=30.0)
            assert result1 is not None
            assert result1.get("promoted") is True
            assert (result1["accepted"] + result1["dropped"]
                    == result1["clock"])
            # convergence without hand-tuning: the assembled trajectory
            # decreases through straggler + promotion + damping
            total = ps.collect_eval(num_worker_procs=2, timeout_s=60.0)
            assert total is not None, "eval plane died"
            traj = total / self.N
            assert traj[-1] < traj[0] * 0.2, traj
            for w in workers:
                rc = w.wait(timeout=60.0)
                assert rc == 0, f"worker exited rc={rc}"
            out = [json.loads(w.stdout.read().splitlines()[-1])
                   for w in workers]
            assert sum(o["gradients"] for o in out) >= self.ITERS
        finally:
            for w in workers:
                if w.poll() is None:
                    w.kill()
            ctl.stop()
            ps.stop()
            group.stop()
