"""MLlib breadth (VERDICT item 7): decision tree, NaiveBayes, PCA/SVD,
evaluation metrics -- each validated against sklearn on fixtures."""

import numpy as np
import pytest

from asyncframework_tpu.ml import (
    PCA,
    BinaryClassificationMetrics,
    DecisionTree,
    MulticlassMetrics,
    NaiveBayes,
    RegressionMetrics,
    svd,
)


@pytest.fixture(scope="module")
def clf_data():
    from sklearn.datasets import make_classification as mk

    X, y = mk(n_samples=1500, n_features=12, n_informative=6, random_state=7,
              n_classes=3, n_clusters_per_class=1)
    return X.astype(np.float32), y


@pytest.fixture(scope="module")
def reg_data():
    rs = np.random.default_rng(3)
    X = rs.normal(size=(1200, 8)).astype(np.float32)
    y = (np.sin(X[:, 0]) * 3 + X[:, 1] ** 2 + 0.1 * rs.normal(size=1200))
    return X, y.astype(np.float32)


class TestDecisionTree:
    def test_classification_close_to_sklearn(self, clf_data):
        from sklearn.tree import DecisionTreeClassifier

        X, y = clf_data
        ours = DecisionTree("classification", max_depth=5, max_bins=64)
        pred = ours.fit(X, y).predict(X)
        acc = (pred == y).mean()
        sk = DecisionTreeClassifier(max_depth=5, random_state=0).fit(X, y)
        sk_acc = (sk.predict(X) == y).mean()
        # binned splits lose a little purity vs exact-threshold sklearn
        assert acc >= sk_acc - 0.06, (acc, sk_acc)
        assert acc > 0.8

    def test_regression_close_to_sklearn(self, reg_data):
        from sklearn.tree import DecisionTreeRegressor

        X, y = reg_data
        pred = DecisionTree("regression", max_depth=5, max_bins=64).fit(
            X, y
        ).predict(X)
        sk_pred = DecisionTreeRegressor(max_depth=5, random_state=0).fit(
            X, y
        ).predict(X)
        ours_r2 = RegressionMetrics.of(pred, y).r2
        sk_r2 = RegressionMetrics.of(sk_pred, y).r2
        assert ours_r2 >= sk_r2 - 0.08, (ours_r2, sk_r2)
        assert ours_r2 > 0.5

    def test_perfect_split_recovered(self):
        rs = np.random.default_rng(0)
        X = rs.normal(size=(400, 3)).astype(np.float32)
        y = (X[:, 1] > 0.3).astype(np.int64)
        model = DecisionTree("classification", max_depth=2, max_bins=128).fit(X, y)
        assert model.feature[0] == 1  # split on the true feature
        assert abs(model.threshold[0] - 0.3) < 0.1
        assert (model.predict(X) == y).mean() > 0.97

    def test_pure_node_stops(self):
        X = np.asarray([[0.0], [1.0], [2.0], [3.0]], np.float32)
        y = np.asarray([0, 0, 1, 1])
        model = DecisionTree("classification", max_depth=4, max_bins=8).fit(X, y)
        assert (model.predict(X) == y).all()
        # children of pure nodes were never split
        assert model.feature[1] == -1 and model.feature[2] == -1


class TestNaiveBayes:
    def test_gaussian_matches_sklearn(self, clf_data):
        from sklearn.naive_bayes import GaussianNB

        X, y = clf_data
        ours = NaiveBayes(model_type="gaussian").fit(X, y).predict(X)
        sk = GaussianNB().fit(X, y).predict(X)
        assert (ours == sk).mean() > 0.97

    def test_multinomial_matches_sklearn(self):
        from sklearn.naive_bayes import MultinomialNB

        rs = np.random.default_rng(1)
        X = rs.poisson(3.0, size=(800, 20)).astype(np.float32)
        w = rs.normal(size=(20,))
        y = (X @ w > np.median(X @ w)).astype(np.int64)
        ours = NaiveBayes(smoothing=1.0, model_type="multinomial").fit(
            X, y
        ).predict(X)
        sk = MultinomialNB(alpha=1.0).fit(X, y).predict(X)
        assert (ours == sk).mean() > 0.99

    def test_bernoulli_matches_sklearn(self):
        from sklearn.naive_bayes import BernoulliNB

        rs = np.random.default_rng(2)
        X = (rs.random((600, 15)) < 0.3).astype(np.float32)
        y = (X[:, :5].sum(1) > 1).astype(np.int64)
        ours = NaiveBayes(smoothing=1.0, model_type="bernoulli").fit(
            X, y
        ).predict(X)
        sk = BernoulliNB(alpha=1.0).fit(X, y).predict(X)
        assert (ours == sk).mean() > 0.99


class TestPCAandSVD:
    def test_pca_matches_sklearn(self, clf_data):
        from sklearn.decomposition import PCA as SKPCA

        X, _ = clf_data
        ours = PCA(4).fit(X)
        sk = SKPCA(4).fit(X)
        # same subspace: compare |cosine| of matching components
        for i in range(4):
            cos = abs(np.dot(ours.components[i], sk.components_[i]))
            assert cos > 0.999, (i, cos)
        np.testing.assert_allclose(
            ours.explained_variance, sk.explained_variance_, rtol=1e-3
        )

    def test_pca_distributed_matches_local(self, devices8, clf_data):
        from asyncframework_tpu.parallel import make_mesh

        X, _ = clf_data
        X = X[:1496]  # divisible by 8
        mesh = make_mesh(8, devices=devices8)
        local = PCA(3).fit(X)
        dist = PCA(3).fit(X, mesh=mesh)
        np.testing.assert_allclose(
            np.abs(dist.components), np.abs(local.components),
            rtol=1e-3, atol=1e-4,
        )

    def test_svd_reconstructs(self, reg_data):
        X, _ = reg_data
        U, s, V = svd(X, k=8)  # full rank: exact reconstruction
        np.testing.assert_allclose(
            np.asarray(U) * s @ V.T, X, atol=5e-3
        )
        # singular values match numpy's
        s_np = np.linalg.svd(X, compute_uv=False)[:8]
        np.testing.assert_allclose(s, s_np, rtol=1e-3)

    def test_svd_truncation_drops_null_directions(self):
        rs = np.random.default_rng(5)
        base = rs.normal(size=(300, 2)).astype(np.float32)
        X = np.hstack([base, base @ rs.normal(size=(2, 3)).astype(np.float32)])
        _, s, V = svd(X, k=5, compute_u=False)
        assert len(s) == 2  # true rank recovered via rcond cut
        assert V.shape == (5, 2)


class TestEvaluation:
    def test_auc_matches_sklearn(self):
        from sklearn.metrics import average_precision_score, roc_auc_score

        rs = np.random.default_rng(4)
        y = (rs.random(2000) < 0.3).astype(np.float32)
        scores = y * 0.5 + rs.normal(0, 0.6, 2000)
        m = BinaryClassificationMetrics(scores, y)
        np.testing.assert_allclose(
            m.area_under_roc(), roc_auc_score(y, scores), atol=1e-4
        )
        # trapezoid AUPRC vs sklearn's step interpolation: close, not equal
        np.testing.assert_allclose(
            m.area_under_pr(), average_precision_score(y, scores), atol=0.02
        )

    def test_regression_metrics_match_sklearn(self, reg_data):
        from sklearn.metrics import (
            mean_absolute_error,
            mean_squared_error,
            r2_score,
        )

        X, y = reg_data
        pred = y + np.random.default_rng(0).normal(0, 0.5, len(y)).astype(
            np.float32
        )
        m = RegressionMetrics.of(pred, y)
        np.testing.assert_allclose(
            m.mean_squared_error, mean_squared_error(y, pred), rtol=1e-4
        )
        np.testing.assert_allclose(
            m.mean_absolute_error, mean_absolute_error(y, pred), rtol=1e-4
        )
        np.testing.assert_allclose(m.r2, r2_score(y, pred), rtol=1e-3)

    def test_multiclass_metrics(self):
        from sklearn.metrics import confusion_matrix, f1_score

        rs = np.random.default_rng(6)
        y = rs.integers(0, 3, 500)
        pred = np.where(rs.random(500) < 0.8, y, rs.integers(0, 3, 500))
        m = MulticlassMetrics(pred, y)
        np.testing.assert_array_equal(
            m.confusion, confusion_matrix(y, pred)
        )
        np.testing.assert_allclose(
            m.weighted_f1(),
            f1_score(y, pred, average="weighted"),
            rtol=1e-6,
        )
        assert 0.7 < m.accuracy <= 1.0


class TestGaussianMixture:
    def test_recovers_separated_blobs(self):
        from asyncframework_tpu.ml import GaussianMixture

        rs = np.random.default_rng(0)
        a = rs.normal([-4, 0], 0.5, size=(300, 2))
        b = rs.normal([4, 1], 0.8, size=(300, 2))
        X = np.vstack([a, b]).astype(np.float32)
        model = GaussianMixture(2, seed=1).fit(X)
        pred = model.predict(X)
        # each blob lands (almost) entirely in one component
        pa = np.bincount(pred[:300], minlength=2)
        pb = np.bincount(pred[300:], minlength=2)
        assert pa.max() > 290 and pb.max() > 290
        assert pa.argmax() != pb.argmax()
        means = np.sort(model.means[:, 0])
        np.testing.assert_allclose(means, [-4, 4], atol=0.3)

    def test_loglik_close_to_sklearn(self):
        from sklearn.mixture import GaussianMixture as SKGMM

        from asyncframework_tpu.ml import GaussianMixture

        rs = np.random.default_rng(2)
        X = np.vstack([
            rs.normal(0, 1, size=(200, 3)),
            rs.normal(3, 1.5, size=(200, 3)),
        ]).astype(np.float32)
        ours = GaussianMixture(2, seed=0, max_iterations=200).fit(X)
        sk = SKGMM(2, random_state=0, max_iter=200).fit(X)
        ours_avg_ll = ours.log_likelihood / len(X)
        assert ours_avg_ll >= sk.score(X) - 0.05

    def test_proba_rows_sum_to_one(self):
        from asyncframework_tpu.ml import GaussianMixture

        rs = np.random.default_rng(3)
        X = rs.normal(size=(100, 2)).astype(np.float32)
        p = GaussianMixture(3, seed=0, max_iterations=10).fit(X).predict_proba(X)
        np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-4)


class TestFPGrowth:
    TXS = [
        ["bread", "milk"],
        ["bread", "diapers", "beer", "eggs"],
        ["milk", "diapers", "beer", "cola"],
        ["bread", "milk", "diapers", "beer"],
        ["bread", "milk", "diapers", "cola"],
    ]

    def brute_force(self, min_support):
        from itertools import combinations

        n = len(self.TXS)
        items = sorted({i for t in self.TXS for i in t})
        out = {}
        for r in range(1, len(items) + 1):
            for combo in combinations(items, r):
                s = frozenset(combo)
                c = sum(1 for t in self.TXS if s <= set(t))
                if c / n >= min_support:
                    out[s] = c
        return out

    @pytest.mark.parametrize("min_support", [0.2, 0.4, 0.6])
    def test_matches_brute_force(self, min_support):
        from asyncframework_tpu.ml import FPGrowth

        model = FPGrowth(min_support).run(self.TXS)
        assert model.freq_itemsets == self.brute_force(min_support)

    def test_association_rules(self):
        from asyncframework_tpu.ml import FPGrowth

        model = FPGrowth(0.4).run(self.TXS)
        rules = model.association_rules(min_confidence=0.9)
        by_pair = {(tuple(sorted(r.antecedent)), tuple(r.consequent)): r
                   for r in rules}
        # beer appears in 3 transactions, all of which contain diapers
        key = (("beer",), ("diapers",))
        assert key in by_pair and by_pair[key].confidence == 1.0


class TestRandomForest:
    def test_forest_beats_single_tree_on_noise(self, clf_data):
        from asyncframework_tpu.ml import RandomForest

        X, y = clf_data
        rs = np.random.default_rng(0)
        flip = rs.random(len(y)) < 0.15
        y_noisy = np.where(flip, rs.integers(0, 3, len(y)), y)
        half = len(y) // 2
        forest = RandomForest(num_trees=15, max_depth=6, seed=3).fit(
            X[:half], y_noisy[:half]
        )
        tree_pred = DecisionTree(max_depth=6).fit(
            X[:half], y_noisy[:half]
        ).predict(X[half:])
        forest_pred = forest.predict(X[half:])
        acc_f = (forest_pred == y[half:]).mean()
        acc_t = (tree_pred == y[half:]).mean()
        assert acc_f >= acc_t - 0.01  # ensemble at least matches, usually beats
        assert acc_f > 0.75

    def test_regression_forest(self, reg_data):
        from asyncframework_tpu.ml import RandomForest, RegressionMetrics

        X, y = reg_data
        model = RandomForest("regression", num_trees=10, max_depth=5,
                             feature_subset_strategy="all", seed=1).fit(X, y)
        r2 = RegressionMetrics.of(model.predict(X), y).r2
        assert r2 > 0.5


class TestSoftmaxRegression:
    def test_multiclass_close_to_sklearn(self, clf_data):
        from sklearn.linear_model import LogisticRegression as SKLR

        from asyncframework_tpu.ml import SoftmaxRegression

        X, y = clf_data
        model = SoftmaxRegression(step_size=0.5, num_iterations=400).fit(X, y)
        acc = (model.predict(X) == y).mean()
        sk_acc = (SKLR(max_iter=400).fit(X, y).predict(X) == y).mean()
        assert acc >= sk_acc - 0.03, (acc, sk_acc)
        # loss monotonically decreasing over the scan (full batch, fixed lr)
        losses = model.loss_history
        assert losses[-1] < losses[0]
        p = model.predict_proba(X[:5])
        np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)


class TestTFIDFandChiSq:
    DOCS = [["tpu", "async", "tpu"], ["async"], ["sgd", "tpu"]]

    def test_hashing_tf_counts(self):
        from asyncframework_tpu.ml import HashingTF

        tf = HashingTF(64)
        M = np.asarray(tf.transform(self.DOCS))
        assert M.shape == (3, 64)
        # row sums = token counts; identical tokens share a bucket
        np.testing.assert_array_equal(M.sum(axis=1), [3, 1, 2])
        tpu_bucket = tf.indices(["tpu"])[0]
        assert M[0, tpu_bucket] == 2

    def test_tf_idf_matches_sklearn_formula(self):
        from asyncframework_tpu.ml import IDF, HashingTF

        tf = HashingTF(128).transform(self.DOCS)
        model = IDF().fit(tf)
        tfidf = np.asarray(model.transform(tf))
        # spot-check the "sgd" term: df=1, idf=log(4/2)
        from asyncframework_tpu.ml import HashingTF as H

        b = H(128).indices(["sgd"])[0]
        np.testing.assert_allclose(tfidf[2, b], np.log(4 / 2), rtol=1e-5)
        # "async": df=2 -> log(4/3)
        b2 = H(128).indices(["async"])[0]
        np.testing.assert_allclose(tfidf[1, b2], np.log(4 / 3), rtol=1e-5)

    def test_min_doc_freq_zeroes_rare_terms(self):
        from asyncframework_tpu.ml import IDF, HashingTF

        tf = HashingTF(128).transform(self.DOCS)
        model = IDF(min_doc_freq=2).fit(tf)
        b = HashingTF(128).indices(["sgd"])[0]  # df=1 < 2
        assert float(model.idf[b]) == 0.0

    def test_chi_sq_goodness_of_fit_matches_scipy(self):
        from scipy.stats import chisquare

        from asyncframework_tpu.ml import chi_sq_test

        obs = [16, 18, 16, 14, 12, 12]
        got = chi_sq_test(obs)
        ref = chisquare(obs)
        np.testing.assert_allclose(got.statistic, ref.statistic, rtol=1e-5)
        np.testing.assert_allclose(got.p_value, ref.pvalue, rtol=1e-4)
        assert got.degrees_of_freedom == 5

    def test_chi_sq_independence_matches_scipy(self):
        from scipy.stats import chi2_contingency

        from asyncframework_tpu.ml import chi_sq_test_matrix

        table = [[10, 20, 30], [6, 9, 17]]
        got = chi_sq_test_matrix(table)
        ref = chi2_contingency(table, correction=False)
        np.testing.assert_allclose(got.statistic, ref.statistic, rtol=1e-5)
        np.testing.assert_allclose(got.p_value, ref.pvalue, rtol=1e-4)
        assert got.degrees_of_freedom == 2


class TestLDA:
    def synthetic_corpus(self, n_docs=200, vocab=40, seed=0):
        """Two planted topics on disjoint vocab halves."""
        rs = np.random.default_rng(seed)
        X = np.zeros((n_docs, vocab), np.float32)
        labels = rs.random(n_docs) < 0.5
        for i in range(n_docs):
            lo, hi = (0, vocab // 2) if labels[i] else (vocab // 2, vocab)
            words = rs.integers(lo, hi, 30)
            np.add.at(X[i], words, 1)
        return X, labels

    def test_recovers_planted_topics(self):
        from asyncframework_tpu.ml import LDA

        X, labels = self.synthetic_corpus()
        model = LDA(k=2, max_iterations=30, seed=1).fit(X)
        # each learned topic concentrates on one vocab half
        half = X.shape[1] // 2
        mass_lo = model.topics[:, :half].sum(axis=1)
        assert (mass_lo > 0.95).any() and (mass_lo < 0.05).any()
        # doc mixtures separate the two doc groups
        t0 = model.doc_topics[labels].argmax(axis=1)
        t1 = model.doc_topics[~labels].argmax(axis=1)
        assert (t0 == np.bincount(t0).argmax()).mean() > 0.95
        assert np.bincount(t0).argmax() != np.bincount(t1).argmax()

    def test_perplexity_decreases_and_transform(self):
        from asyncframework_tpu.ml import LDA

        X, _ = self.synthetic_corpus(seed=3)
        model = LDA(k=2, max_iterations=25, seed=2).fit(X)
        h = model.log_perplexity_history
        assert h[-1] < h[0]
        mix = model.transform(X[:10])
        np.testing.assert_allclose(mix.sum(axis=1), 1.0, rtol=1e-4)
        terms, weights = model.describe_topics(5)[0]
        assert len(terms) == 5 and (np.diff(weights) <= 1e-9).all()

    def test_chi_sq_rejects_zero_expected(self):
        from asyncframework_tpu.ml import chi_sq_test, chi_sq_test_matrix

        with pytest.raises(ValueError, match="expected"):
            chi_sq_test([5, 3], expected=[1, 0])
        with pytest.raises(ValueError, match="positive total"):
            chi_sq_test_matrix([[0, 0], [3, 4]])

    def test_chi_sq_extreme_p_not_underflowed_to_garbage(self):
        from asyncframework_tpu.ml import chi_sq_test

        res = chi_sq_test([1000, 10])
        assert res.statistic > 900
        assert 0.0 <= res.p_value < 1e-30  # survival fn, not 1 - cdf

    def test_empty_corpus_flows(self):
        from asyncframework_tpu.ml import IDF, HashingTF

        tf = HashingTF(32).transform([])
        assert np.asarray(tf).shape == (0, 32)
        IDF().fit(tf)  # no crash


class TestGradientBoostedTrees:
    def test_regression_beats_single_tree(self, reg_data):
        from asyncframework_tpu.ml import GradientBoostedTrees

        X, y = reg_data
        gbt = GradientBoostedTrees("regression", num_iterations=30,
                                   learning_rate=0.2, max_depth=3).fit(X, y)
        tree = DecisionTree("regression", max_depth=3).fit(X, y)
        gbt_r2 = RegressionMetrics.of(gbt.predict(X), y).r2
        tree_r2 = RegressionMetrics.of(tree.predict(X), y).r2
        assert gbt_r2 > tree_r2 + 0.05
        assert gbt_r2 > 0.7

    def test_classification_close_to_sklearn(self, clf_data):
        from sklearn.ensemble import GradientBoostingClassifier

        from asyncframework_tpu.ml import GradientBoostedTrees

        X, y3 = clf_data
        y = (y3 > 0).astype(np.int64)  # binary
        ours = GradientBoostedTrees("classification", num_iterations=30,
                                    learning_rate=0.2, max_depth=3).fit(X, y)
        acc = (ours.predict(X) == y).mean()
        sk = GradientBoostingClassifier(n_estimators=30, learning_rate=0.2,
                                        max_depth=3, random_state=0).fit(X, y)
        sk_acc = (sk.predict(X) == y).mean()
        assert acc >= sk_acc - 0.05, (acc, sk_acc)

    def test_rejects_bad_labels(self, reg_data):
        from asyncframework_tpu.ml import GradientBoostedTrees

        X, y = reg_data
        with pytest.raises(ValueError, match="labels"):
            GradientBoostedTrees("classification").fit(X, y)


class TestModelPersistence:
    def test_round_trip_every_family(self, clf_data, reg_data, tmp_path):
        from asyncframework_tpu.ml import (
            GaussianMixture,
            GradientBoostedTrees,
            KMeans,
            NaiveBayes,
            PCA,
            RandomForest,
            SoftmaxRegression,
            load_model,
            save_model,
        )
        from asyncframework_tpu.ml.recommendation import ALS

        X, y = clf_data
        Xr, yr = reg_data
        Xs = X[:300]
        ys = y[:300]
        rs = np.random.default_rng(0)
        R = ((rs.random((20, 15)) < 0.4) * rs.random((20, 15))).astype(
            np.float32
        )

        models = {
            "tree": DecisionTree(max_depth=3).fit(Xs, ys),
            "forest": RandomForest(num_trees=3, max_depth=3).fit(Xs, ys),
            "gbt": GradientBoostedTrees("regression", num_iterations=3).fit(
                Xr[:300], yr[:300]
            ),
            "nb": NaiveBayes(model_type="gaussian").fit(Xs, ys),
            "nbm": NaiveBayes(model_type="multinomial").fit(np.abs(Xs), ys),
            "kmeans": KMeans(3, seed=0).fit(Xs),
            "pca": PCA(2).fit(Xs),
            "gmm": GaussianMixture(2, max_iterations=5, seed=0).fit(Xs[:, :3]),
            "softmax": SoftmaxRegression(num_iterations=20).fit(Xs, ys),
            "als": ALS(rank=3, num_iterations=3).fit(R),
        }
        for name, model in models.items():
            p = save_model(model, tmp_path / name)
            loaded = load_model(p)
            assert type(loaded).__name__ == type(model).__name__
            if name == "als":  # different-signature predict
                np.testing.assert_allclose(
                    loaded.predict([0, 1], [2, 3]), model.predict([0, 1], [2, 3])
                )
                continue
            if name == "pca":  # transform, not predict
                np.testing.assert_allclose(
                    np.asarray(loaded.transform(Xs[:20])),
                    np.asarray(model.transform(Xs[:20])), rtol=1e-6,
                )
                continue
            feed = Xr[:20] if name == "gbt" else (
                np.abs(Xs[:20]) if name == "nbm" else
                (Xs[:20, :3] if name == "gmm" else Xs[:20])
            )
            np.testing.assert_allclose(
                np.asarray(model.predict(feed), np.float64),
                np.asarray(loaded.predict(feed), np.float64),
                rtol=1e-6,
            )

    def test_linear_models_round_trip(self, tmp_path):
        from asyncframework_tpu.ml import load_model, save_model
        from asyncframework_tpu.ml.models import LogisticRegressionModel

        m = LogisticRegressionModel(
            weights=np.asarray([0.5, -1.0], np.float32), intercept=0.25,
            loss_history=np.asarray([1.0, 0.5]), weight_history=[],
        )
        p = save_model(m, tmp_path / "lr")
        loaded = load_model(p)
        X = np.asarray([[1.0, 1.0], [-2.0, 0.5]], np.float32)
        np.testing.assert_allclose(loaded.predict(X), m.predict(X))

    def test_save_as_libsvm_round_trip(self, tmp_path):
        from asyncframework_tpu.data import load_libsvm
        from asyncframework_tpu.ml import save_as_libsvm_file

        rs = np.random.default_rng(1)
        X = (rs.random((20, 6)) < 0.4) * rs.normal(size=(20, 6))
        X = X.astype(np.float32)
        y = rs.normal(size=20).astype(np.float32)
        p = save_as_libsvm_file(X, y, tmp_path / "d.libsvm")
        X2, y2 = load_libsvm(str(p), num_features=6, use_native=False)
        # %.9g writes full float32 precision: exact round trip
        np.testing.assert_array_equal(X2, X)
        np.testing.assert_array_equal(y2, y)

    def test_unknown_class_rejected(self, tmp_path):
        from asyncframework_tpu.ml import save_model

        with pytest.raises(TypeError, match="no persistence"):
            save_model(object(), tmp_path / "x")


class TestRegressionVariantsAndTests:
    def test_ridge_and_lasso(self, reg_data):
        from asyncframework_tpu.ml import Lasso, RidgeRegression

        rs = np.random.default_rng(0)
        X = rs.normal(size=(600, 10)).astype(np.float32)
        w_true = np.zeros(10, np.float32)
        w_true[:3] = [2.0, -1.5, 1.0]  # sparse truth for the lasso
        y = (X @ w_true + 0.05 * rs.normal(size=600)).astype(np.float32)
        ridge = RidgeRegression(step_size=0.1, num_iterations=300,
                                reg_param=0.01).fit(X, y)
        lasso = Lasso(step_size=0.1, num_iterations=300,
                      reg_param=0.05).fit(X, y)
        np.testing.assert_allclose(ridge.weights[:3], w_true[:3], atol=0.2)
        # L1 drives the dead coefficients toward exactly zero
        assert np.abs(lasso.weights[3:]).max() < 0.05
        assert np.abs(ridge.weights[3:]).max() < 0.2

    def test_isotonic_matches_sklearn(self):
        from sklearn.isotonic import IsotonicRegression as SKIso

        from asyncframework_tpu.ml import IsotonicRegression

        rs = np.random.default_rng(1)
        x = np.sort(rs.random(200) * 10)
        y = np.log1p(x) + rs.normal(0, 0.15, 200)
        ours = IsotonicRegression().fit(x, y)
        sk = SKIso(out_of_bounds="clip").fit(x, y)
        grid = np.linspace(0, 10, 50)
        np.testing.assert_allclose(
            ours.predict(grid), sk.predict(grid), atol=1e-6
        )

    def test_isotonic_decreasing_and_weights(self):
        from asyncframework_tpu.ml import IsotonicRegression

        x = np.asarray([1.0, 2, 3, 4])
        y = np.asarray([4.0, 3, 3.5, 1])
        m = IsotonicRegression(increasing=False).fit(x, y)
        pred = m.predict(x)
        assert all(a >= b - 1e-9 for a, b in zip(pred, pred[1:]))
        with pytest.raises(ValueError, match="positive"):
            IsotonicRegression().fit(x, y, weights=[1, 0, 1, 1])

    def test_ks_test_matches_scipy(self):
        from scipy.stats import kstest

        from asyncframework_tpu.ml import ks_test

        rs = np.random.default_rng(2)
        sample = rs.normal(0.2, 1.0, 400)
        got = ks_test(sample, "norm")
        ref = kstest(sample, "norm")
        np.testing.assert_allclose(got.statistic, ref.statistic, rtol=1e-6)
        np.testing.assert_allclose(got.p_value, ref.pvalue, rtol=0.05)
        # agreement with scipy on a null-true sample as well (an absolute
        # p > 0.05 assertion would fail ~5% of seeds by definition)
        s2 = rs.normal(0, 1, 400)
        got2 = ks_test(s2, "norm")
        ref2 = kstest(s2, "norm")
        np.testing.assert_allclose(got2.statistic, ref2.statistic, rtol=1e-6)
        np.testing.assert_allclose(got2.p_value, ref2.pvalue, rtol=0.05)

    def test_isotonic_ties_pooled_and_persist(self, tmp_path):
        from sklearn.isotonic import IsotonicRegression as SKIso

        from asyncframework_tpu.ml import (
            IsotonicRegression,
            load_model,
            save_model,
        )

        x = np.asarray([1.0, 1.0, 2.0])
        y = np.asarray([0.0, 1.0, 2.0])
        m = IsotonicRegression().fit(x, y)
        sk = SKIso(out_of_bounds="clip").fit(x, y)
        np.testing.assert_allclose(m.predict([1.0]), sk.predict([1.0]))
        loaded = load_model(save_model(m, tmp_path / "iso"))
        grid = np.linspace(0.5, 2.5, 9)
        np.testing.assert_allclose(loaded.predict(grid), m.predict(grid))


class TestPipelineAndTuning:
    def test_pipeline_scaler_into_classifier(self, clf_data):
        from asyncframework_tpu.ml import (
            DecisionTree,
            Pipeline,
            StandardScaler,
            accuracy_scorer,
            train_test_split,
        )

        X, y = clf_data
        Xtr, ytr, Xte, yte = train_test_split(X, y, 0.3, seed=0)
        model = Pipeline([
            StandardScaler(),
            DecisionTree(max_depth=5, max_bins=64),
        ]).fit(Xtr, ytr)
        acc = accuracy_scorer(model, Xte, yte)
        assert acc > 0.75
        # the fitted scaler travels with the model
        assert model.transformers[0].mean_ is not None

    def test_pipeline_rejects_bad_shapes(self):
        from asyncframework_tpu.ml import DecisionTree, Pipeline

        with pytest.raises(ValueError):
            Pipeline([])
        with pytest.raises(TypeError, match="transform"):
            Pipeline([DecisionTree(), DecisionTree()]).fit(
                np.zeros((4, 2), np.float32), np.zeros(4)
            )

    def test_cross_validator_picks_better_depth(self, clf_data):
        from asyncframework_tpu.ml import (
            CrossValidator,
            DecisionTree,
            accuracy_scorer,
        )

        X, y = clf_data
        cv = CrossValidator(
            estimator_factory=lambda max_depth: DecisionTree(
                max_depth=max_depth, max_bins=32
            ),
            param_grid={"max_depth": [1, 5]},
            scorer=accuracy_scorer,
            num_folds=3,
            seed=1,
        ).fit(X[:900], y[:900])
        assert cv.best_params == {"max_depth": 5}
        assert len(cv.all_scores) == 2
        scores = dict((tuple(p.items()), s) for p, s in cv.all_scores)
        assert scores[(("max_depth", 5),)] > scores[(("max_depth", 1),)]
        assert (cv.predict(X[:50]) == y[:50]).mean() > 0.7

    def test_train_test_split_partitions(self):
        from asyncframework_tpu.ml import train_test_split

        X = np.arange(40).reshape(20, 2)
        y = np.arange(20)
        Xtr, ytr, Xte, yte = train_test_split(X, y, 0.25, seed=3)
        assert len(Xte) == 5 and len(Xtr) == 15
        assert sorted(np.concatenate([ytr, yte]).tolist()) == list(range(20))

    def test_pipeline_model_persists(self, clf_data, tmp_path):
        from asyncframework_tpu.ml import (
            DecisionTree,
            Pipeline,
            StandardScaler,
            load_model,
            save_model,
        )

        X, y = clf_data
        pipe = Pipeline([StandardScaler(), DecisionTree(max_depth=4)]).fit(
            X[:600], y[:600]
        )
        p = save_model(pipe, tmp_path / "pipe")
        loaded = load_model(p)
        np.testing.assert_array_equal(
            loaded.predict(X[600:700]), pipe.predict(X[600:700])
        )


class TestPowerIterationClustering:
    def test_two_blocks_recovered(self):
        from asyncframework_tpu.ml import PowerIterationClustering

        rs = np.random.default_rng(0)
        n = 60
        W = np.zeros((n, n), np.float32)
        # two dense blocks with weak cross links
        for lo, hi in [(0, 30), (30, 60)]:
            block = rs.random((30, 30)) * 0.9 + 0.1
            W[lo:hi, lo:hi] = (block + block.T) / 2
        W += rs.random((n, n)).astype(np.float32) * 0.02
        W = (W + W.T) / 2
        np.fill_diagonal(W, 0.0)
        labels = PowerIterationClustering(2, max_iterations=40).fit_predict(W)
        a, b = labels[:30], labels[30:]
        assert (a == np.bincount(a).argmax()).mean() > 0.9
        assert np.bincount(a).argmax() != np.bincount(b).argmax()

    def test_rejects_bad_affinity(self):
        from asyncframework_tpu.ml import PowerIterationClustering

        with pytest.raises(ValueError, match="square"):
            PowerIterationClustering(2).fit_predict(np.ones((3, 4)))
        with pytest.raises(ValueError, match="nonnegative"):
            PowerIterationClustering(2).fit_predict(
                np.asarray([[0.0, -1.0], [-1.0, 0.0]])
            )


class TestWord2Vec:
    def corpus(self, n=400, seed=0):
        """Two topic groups whose words co-occur only within the group."""
        rs = np.random.default_rng(seed)
        tech = ["chip", "mesh", "ici", "hbm", "kernel", "compile"]
        food = ["bread", "milk", "butter", "cheese", "apple", "flour"]
        sents = []
        for _ in range(n):
            group = tech if rs.random() < 0.5 else food
            sents.append(list(rs.choice(group, size=6)))
        return sents, tech, food

    def test_groups_separate_in_embedding_space(self):
        from asyncframework_tpu.ml import Word2Vec

        sents, tech, food = self.corpus()
        model = Word2Vec(vector_size=16, window=3, min_count=2,
                         num_iterations=25, learning_rate=0.3,
                         batch_size=256, seed=1).fit(sents)
        # within-group similarity dominates cross-group
        win, cross = [], []
        for a in tech:
            for b in tech:
                if a != b:
                    win.append(model.similarity(a, b))
            for b in food:
                cross.append(model.similarity(a, b))
        assert np.mean(win) > np.mean(cross) + 0.2

    def test_find_synonyms_prefers_same_group(self):
        from asyncframework_tpu.ml import Word2Vec

        sents, tech, food = self.corpus(seed=2)
        model = Word2Vec(vector_size=16, window=3, num_iterations=25,
                         learning_rate=0.3, batch_size=256, seed=3).fit(sents)
        top = [w for w, _ in model.find_synonyms("chip", 3)]
        assert all(w in tech for w in top), top
        assert "chip" not in top

    def test_vocab_and_errors(self):
        from asyncframework_tpu.ml import Word2Vec

        sents, _, _ = self.corpus()
        model = Word2Vec(vector_size=8, num_iterations=1, seed=0).fit(sents)
        assert "chip" in model and "nonexistent" not in model
        with pytest.raises(KeyError):
            model.transform("nonexistent")
        with pytest.raises(ValueError, match="vocabulary"):
            Word2Vec(min_count=100).fit([["a", "b"]])

    def test_cv_rejects_all_nan_and_split_guards(self):
        from asyncframework_tpu.ml import (
            CrossValidator,
            DecisionTree,
            train_test_split,
        )

        X = np.random.default_rng(0).normal(size=(30, 3)).astype(np.float32)
        y = np.zeros(30)
        nan_scorer = lambda m, Xv, yv: float("nan")  # noqa: E731
        with pytest.raises(ValueError, match="NaN"):
            CrossValidator(
                lambda max_depth: DecisionTree(max_depth=max_depth),
                {"max_depth": [2]}, nan_scorer, 3,
            ).fit(X, y)
        with pytest.raises(ValueError, match="empty partition"):
            train_test_split(X[:2], y[:2], test_fraction=0.1)
