"""Sharded parameter server with elastic shard failover (ISSUE 8).

The correctness spine:

- ``shards=1`` IS the classic single PS: the conf knob being set must be
  byte-identical on the wire (per-op frame-byte totals) and
  step-identical (accepted/dropped/staleness/clock) to the knob being
  absent, under a fixed seed;
- the staleness contract is a per-shard VECTOR: every pull returns one
  clock per shard, every sub-push is priced against its own shard's
  clock, and a shard whose clock runs ahead (direct out-of-band pushes)
  prices staleness higher than its peers -- independently;
- the serving tier degrades per range: a dark range keeps its last
  validated slice (partial refresh), freshness prices the STALEST range,
  and UNHEALTHY names the stale ranges instead of serving a torn model;
- the acceptance run (`shard` marker, rides every bin/chaos_sweep.py
  seed): an ASGD run over a 3-shard group of REAL OS processes survives
  SIGKILL of one shard mid-run -- the controller's supervisor detects the
  death (pid probe / port silence), relaunches the shard on its pinned
  port from its durable checkpoint (model + clock + dedup window), the
  wire-window machinery replays in-flight pushes onto the recovered
  shard exactly-once, and the run completes with full coverage.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu import conf as conf_mod
from asyncframework_tpu.conf import AsyncConf, global_conf, set_global_conf
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.net import frame, reset_net_totals
from asyncframework_tpu.net.retry import reset_breakers
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel import shardgroup as sg
from asyncframework_tpu.solvers import SolverConfig

pytestmark = pytest.mark.shard

CHILD = Path(__file__).parent / "ps_dcn_child.py"
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


def make_cfg(**kw):
    defaults = dict(
        num_workers=4, num_iterations=120, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.5, printer_freq=40, seed=42,
        calibration_iters=10, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    """Wire-byte totals, shard totals, breakers, and the global conf are
    process-global; runs must neither inherit nor leak them."""
    reset_net_totals()
    sg.reset_shard_totals()
    reset_breakers()
    set_global_conf(AsyncConf())
    yield
    reset_net_totals()
    sg.reset_shard_totals()
    reset_breakers()
    set_global_conf(None)


def run_group(devices, cfg, shards, n=1024, d=23, seed=7, conf=None,
              checkpoint_dir=None):
    """One in-process shard group + worker run; returns (ps_list, counts,
    total) with every PS stopped."""
    if conf is not None:
        set_global_conf(conf)
    ds = ShardedDataset.generate_on_device(
        n, d, cfg.num_workers, devices=devices[:1], seed=seed, noise=0.01)
    ps_list, smap = sg.launch_inprocess_group(
        cfg, d, n, shards, device=devices[0],
        checkpoint_dir=checkpoint_dir)
    try:
        shards_data = {w: ds.shard(w) for w in range(cfg.num_workers)}
        counts = ps_dcn.run_worker_process(
            "127.0.0.1", ps_list[0].port, list(range(cfg.num_workers)),
            shards_data, cfg, d, n, eval_wid=0, deadline_s=120.0)
        assert ps_list[0].wait_done(timeout_s=10.0)
        total = ps_list[0].collect_eval(num_worker_procs=1, timeout_s=30.0)
        return ps_list, smap, counts, total
    finally:
        for ps in ps_list:
            ps.stop()


# --------------------------------------------------- global_conf() footgun
class TestGlobalConfInstall:
    def test_lazily_created_conf_is_installed(self):
        """`global_conf().set(...)` on a process that never called
        set_global_conf must STICK: the lazily-created default is
        installed, not discarded (the lost-write footgun)."""
        set_global_conf(None)
        global_conf().set("async.pull.mode", "delta")
        assert global_conf().contains("async.pull.mode")
        assert global_conf().get("async.pull.mode") == "delta"
        # and it is the SAME instance on every later call
        assert global_conf() is global_conf()

    def test_explicit_install_still_wins(self):
        set_global_conf(None)
        _ = global_conf()  # lazily installed
        mine = AsyncConf().set("async.pull.mode", "full")
        set_global_conf(mine)
        assert global_conf() is mine


# ------------------------------------------------------- ranges + map units
class TestShardRanges:
    def test_cover_and_contiguous(self):
        for d, s in [(24, 3), (23, 3), (7, 7), (100, 8), (5, 1)]:
            ranges = sg.shard_ranges(d, s)
            assert ranges[0][0] == 0 and ranges[-1][1] == d
            for (a, b), (c, e) in zip(ranges, ranges[1:]):
                assert b == c and b > a and e > c

    def test_clamped_to_d(self):
        assert len(sg.shard_ranges(3, 8)) == 3

    def test_remainder_spread(self):
        sizes = [hi - lo for lo, hi in sg.shard_ranges(23, 3)]
        assert sizes == [8, 8, 7]


class TestShardMap:
    def test_wire_round_trip(self):
        m = sg.ShardMap([("a", 1, 0, 8), ("b", 2, 8, 16)])
        assert sg.ShardMap.from_wire(m.to_wire()).entries == m.entries
        assert m.n_shards == 2 and m.d == 16
        assert m.ranges() == [(0, 8), (8, 16)]

    @pytest.mark.parametrize("entries", [
        [],                                        # empty
        [("a", 1, 0, 8), ("b", 2, 9, 16)],         # hole
        [("a", 1, 0, 8), ("b", 2, 4, 16)],         # overlap
        [("a", 1, 0, 8), ("b", 2, 8, 8)],          # empty range
        [("a", 1, 1, 8)],                          # does not start at 0
    ])
    def test_invalid_maps_rejected(self, entries):
        with pytest.raises(ValueError):
            sg.ShardMap(entries)


# --------------------------------------------------- shards=1 byte identity
class TestShardsOneIsClassic:
    def test_conf_set_matches_unset_byte_identical(self, devices8):
        """`async.ps.shards=1` must leave the wire byte-identical and the
        run step-identical to the knob being absent: one worker, full
        pulls, calibration off -- the whole exchange is deterministic, so
        per-op frame-byte totals must match EXACTLY."""
        results = []
        for shards_conf in (None, "1"):
            conf = (AsyncConf().set("async.pull.mode", "full")
                    .set("async.trace.sample", 0.0))
            if shards_conf is not None:
                conf.set("async.ps.shards", shards_conf)
            set_global_conf(conf)
            reset_net_totals()
            cfg = make_cfg(num_workers=1, num_iterations=40,
                           calibration_iters=10**9, bucket_ratio=0.0)
            ds = ShardedDataset.generate_on_device(
                512, 16, 1, devices=devices8[:1], seed=11, noise=0.01)
            ps_list, smap = sg.launch_inprocess_group(
                cfg, 16, 512, max(1, int(shards_conf or 1)),
                device=devices8[0])
            assert smap is None  # shards=1: no map, classic PS
            ps = ps_list[0]
            try:
                counts = ps_dcn.run_worker_process(
                    "127.0.0.1", ps.port, [0], {0: ds.shard(0)}, cfg,
                    16, 512, deadline_s=120.0)
                assert ps.wait_done(timeout_s=10.0)
            finally:
                ps.stop()
            results.append({
                "accepted": ps.accepted, "dropped": ps.dropped,
                "max_staleness": ps.max_staleness, "clock": ps._clock,
                "pull_replies": dict(ps.pull_replies),
                "counts": dict(counts),
                "bytes": frame.bytes_totals(),
            })
        unset, one = results
        assert unset["accepted"] == one["accepted"] == 40
        assert unset == one, (unset, one)

    def test_welcome_carries_no_map_on_classic_ps(self, devices8):
        cfg = make_cfg(num_workers=1, num_iterations=5, bucket_ratio=0.0)
        ps = ps_dcn.ParameterServer(cfg, 8, 64, device=devices8[0],
                                    port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port)
            welcome = cl.hello("t-proc", [0], pid=os.getpid())
            assert "shards" not in welcome
            assert sg.fetch_shard_map("127.0.0.1", ps.port) is None
            cl.bye()
        finally:
            ps.stop()


# ------------------------------------------------ sharded run + vector clock
class TestShardedRun:
    def test_three_shard_run_converges_full_coverage(self, devices8):
        cfg = make_cfg()
        ps_list, smap, counts, total = run_group(devices8, cfg, 3)
        primary = ps_list[0]
        assert primary.accepted == cfg.num_iterations
        # full coverage: every logical worker contributed to the primary
        assert set(primary.accepted_by_wid) == set(range(cfg.num_workers))
        # every secondary applied at least the primary's accepted count
        # (they also take the tail pushes the primary drops post-done)
        for ps in ps_list[1:]:
            assert ps.accepted >= primary.accepted
        assert sum(counts.values()) >= cfg.num_iterations
        traj = total / 1024
        assert traj[-1] < traj[0] * 0.1, traj
        totals = sg.shard_totals()
        assert totals["sharded_pulls"] > 0
        assert totals["sharded_pushes"] >= cfg.num_iterations

    def test_pull_returns_version_vector(self, devices8):
        """The facade's pull ts is a per-shard clock TUPLE; pushes carry
        it back and the assembled model is the concatenation of the
        per-range slices at those versions."""
        cfg = make_cfg(num_workers=1, num_iterations=50, bucket_ratio=0.0,
                       calibration_iters=10**9)
        n, d = 256, 23
        ps_list, smap = sg.launch_inprocess_group(cfg, d, n, 3,
                                                  device=devices8[0])
        try:
            cl = sg.ShardedPSClient(smap)
            got = cl.pull(0)
            assert got is not None
            ts, w, _ms, _cal = got
            assert isinstance(ts, tuple) and len(ts) == 3
            assert w.shape == (d,)
            # direct per-shard pulls agree with the assembled slices
            for i, (h, p, lo, hi) in enumerate(smap.entries):
                direct = ps_dcn.PSClient(h, p)
                got_i = direct.pull(0)
                assert got_i is not None
                ts_i, w_i, _m, _c = got_i
                assert ts_i == ts[i]
                np.testing.assert_array_equal(w_i, w[lo:hi])
                direct.bye()
            g = np.random.default_rng(0).normal(size=d).astype(np.float32)
            accepted, done = cl.push(0, ts, g)
            assert accepted and not done
            cl.bye()
        finally:
            for ps in ps_list:
                ps.stop()

    def test_per_shard_staleness_is_independent(self, devices8):
        """Drive ONE shard's clock ahead with direct out-of-band pushes:
        a facade push stamped with the (now stale) vector must price the
        staleness per shard -- the driven shard records a HIGHER
        staleness than its peers, and the existing staleness metrics
        (max_staleness) surface it per shard."""
        cfg = make_cfg(num_workers=2, num_iterations=10**6,
                       bucket_ratio=0.0, calibration_iters=10**9)
        n, d = 256, 24
        ps_list, smap = sg.launch_inprocess_group(cfg, d, n, 3,
                                                  device=devices8[0])
        try:
            cl = sg.ShardedPSClient(smap)
            got = cl.pull(0)
            ts, w, _ms, _cal = got
            # out-of-band: advance shard 1's clock by 5 direct pushes
            h1, p1, lo1, hi1 = smap.entries[1]
            direct = ps_dcn.PSClient(h1, p1)
            for _ in range(5):
                dts, dw, _m, _c = direct.pull(1)
                direct.push(1, dts, np.ones(hi1 - lo1, np.float32))
            direct.bye()
            # the next facade pull sees the skewed vector
            ts2 = cl.pull(0)[0]
            assert ts2[1] >= ts[1] + 5
            assert ts2[0] <= ts2[1] - 5 + 1
            # a push stamped with the OLD vector: shard 1 prices the 5
            # out-of-band merges as staleness; shard 0/2 price ~0
            cl.push(0, ts, np.ones(d, np.float32))
            assert ps_list[1].max_staleness >= 5
            assert ps_list[0].max_staleness <= 2
            assert ps_list[2].max_staleness <= 2
            cl.bye()
        finally:
            for ps in ps_list:
                ps.stop()


# --------------------------------------------------------- serving per range
class TestShardedSubscriber:
    def _group(self, devices8, **cfg_kw):
        cfg = make_cfg(num_workers=1, num_iterations=10**6,
                       bucket_ratio=0.0, calibration_iters=10**9, **cfg_kw)
        n, d = 256, 24
        ps_list, smap = sg.launch_inprocess_group(cfg, d, n, 3,
                                                  device=devices8[0])
        return ps_list, smap, n, d

    def test_assembled_subscribe_matches_direct_pull(self, devices8):
        ps_list, smap, n, d = self._group(devices8)
        try:
            sub = sg.ShardedSubscriber(smap)
            ts, w, clock, k, age, done = sub.subscribe()
            assert w.shape == (d,) and not done
            direct = sg.ShardedPSClient(smap)
            got = direct.pull(0)
            np.testing.assert_array_equal(got[1], w)
            assert ts == sum(got[0])
            direct.bye()
            assert sub.stale_ranges(10_000.0) == []
            assert sub.oldest_ok_age_ms() is not None
            status = sub.range_status()
            assert [s["shard"] for s in status] == [0, 1, 2]
            assert [(s["lo"], s["hi"]) for s in status] == smap.ranges()
            sub.bye()
        finally:
            for ps in ps_list:
                ps.stop()

    def test_dark_range_partial_refresh_prices_stalest(self, devices8):
        """Kill one shard: the subscriber keeps serving the assembled
        model from the live ranges + the dead range's last validated
        slice, with age pricing the DARK range -- and stale_ranges names
        it (the UNHEALTHY-per-range answer)."""
        ps_list, smap, n, d = self._group(devices8)
        try:
            sub = sg.ShardedSubscriber(smap)
            ts0, w0, *_ = sub.subscribe()
            ps_list[1].stop()  # range 1 goes dark
            time.sleep(0.05)
            ts1, w1, clock1, k1, age1, _done = sub.subscribe()
            assert w1.shape == (d,)
            lo1, hi1 = smap.ranges()[1]
            np.testing.assert_array_equal(w1[lo1:hi1], w0[lo1:hi1])
            time.sleep(1.0)
            _ts, _w, _c, _k, age2, _d = sub.subscribe()
            assert age2 >= 1000.0  # the dark range's age keeps growing
            # UNHEALTHY-per-range: only the dark range is named (the
            # dead-range probe's bounded backoff must not smear onto the
            # live ranges just refreshed this round)
            assert sub.stale_ranges(800.0) == [1]
            sub.bye()
        finally:
            for ps in ps_list:
                ps.stop()

    def test_replica_resolves_group_and_serves(self, devices8):
        """serving/replica.py end-to-end over a shard group: the replica
        resolves the map via SHARDMAP, refreshes through the
        ShardedSubscriber, answers PREDICT, and its STATUS carries the
        per-range freshness surface."""
        from asyncframework_tpu.serving.replica import ModelReplica

        ps_list, smap, n, d = self._group(devices8, loss="least_squares")
        rep = None
        try:
            rep = ModelReplica("127.0.0.1", ps_list[0].port, port=0,
                               refresh_interval_s=0.05,
                               max_stale_ms=5000.0).start()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                st = rep.status()
                if st.get("ts") is not None:
                    break
                time.sleep(0.05)
            st = rep.status()
            assert st.get("ranges") is not None and len(st["ranges"]) == 3
            assert st.get("stale_ranges") == []
            X = np.ascontiguousarray(
                np.random.default_rng(1).normal(size=(4, d)), np.float32)
            sock = frame.connect(("127.0.0.1", rep.port))
            try:
                frame.send_msg(sock, {"op": "PREDICT", "n": 4}, X.tobytes())
                hdr, payload = frame.recv_msg(sock)
            finally:
                sock.close()
            assert hdr["op"] == "PREDICTION", hdr
            out = np.frombuffer(payload, np.float32)
            assert out.shape == (4,) and np.all(np.isfinite(out))
        finally:
            if rep is not None:
                rep.stop()
            for ps in ps_list:
                ps.stop()


# ------------------------------------------------------------ k8s rendering
class TestK8sRendering:
    def test_ps_shard_objects(self):
        from asyncframework_tpu.deploy.k8s import (
            PS_SHARD_PORT,
            render_ps_shards,
        )

        objs = render_ps_shards(3, 24, 2048, workers=8)
        kinds = [o["kind"] for o in objs]
        assert kinds.count("Deployment") == 3
        assert kinds.count("Service") == 3
        assert kinds.count("PersistentVolumeClaim") == 3
        deps = [o for o in objs if o["kind"] == "Deployment"]
        maps = set()
        for i, dep in enumerate(deps):
            meta = dep["spec"]["template"]["metadata"]
            assert meta["annotations"]["prometheus.io/scrape"] == "true"
            assert meta["labels"]["shard"] == str(i)
            env = {e["name"]: e["value"] for e in
                   dep["spec"]["template"]["spec"]["containers"][0]["env"]}
            assert env["ASYNC_SHARD_INDEX"] == str(i)
            assert env["ASYNC_SHARD_COUNT"] == "3"
            assert env["ASYNC_SHARD_ELASTIC"] == ("1" if i == 0 else "0")
            maps.add(env["ASYNC_SHARD_MAP"])
            json.loads(env["ASYNC_SHARD_CFG"])  # valid SolverConfig dict
        # every pod carries the SAME static map, valid and contiguous
        assert len(maps) == 1
        wire = json.loads(maps.pop())
        smap = sg.ShardMap.from_wire(wire)
        assert smap.d == 24
        assert all(p == PS_SHARD_PORT for (_h, p, _l, _r) in smap.entries)
        assert [h for (h, _p, _l, _r) in smap.entries] == [
            f"async-ps-shard-{i}" for i in range(3)]

    def test_render_cluster_includes_shards(self):
        from asyncframework_tpu.deploy.k8s import render_cluster

        files = render_cluster(2, ps_shards=3, ps_d=24, ps_n=2048)
        assert "ps-shards.yaml" in files
        import yaml

        docs = [d for d in yaml.safe_load_all(files["ps-shards.yaml"])
                if d is not None]
        assert len(docs) == 9  # 3 x (PVC + Deployment + Service)
        assert "async-ps-shard-0" in files["ps-shards.yaml"]

    def test_rejects_bad_shapes(self):
        from asyncframework_tpu.deploy.k8s import render_ps_shards

        with pytest.raises(ValueError):
            render_ps_shards(1, 24, 2048)
        with pytest.raises(ValueError):
            render_ps_shards(8, 4, 2048)


# ------------------------------------------------- telemetry + SLO plumbing
class TestTelemetryAndSLO:
    def test_default_rules_include_shard_availability(self):
        from asyncframework_tpu.metrics.slo import parse_rules

        rules = parse_rules(AsyncConf().get(conf_mod.SLO_RULES))
        byname = {r.name: r for r in rules}
        assert "shard_availability" in byname
        rule = byname["shard_availability"]
        assert rule.series == "ps_shards.dark_ranges"
        assert rule.unless_series == "ps_shards.done"

    def test_shard_availability_fires_on_dark_range(self):
        """Drive the ps_shards.dark_ranges series through healthy ->
        dark -> recovered and assert the default rule burns into firing
        and stands back down (no wedge)."""
        from asyncframework_tpu.metrics.slo import (
            FIRING,
            OK,
            SLOEngine,
            parse_rules,
        )
        from asyncframework_tpu.metrics.timeseries import TimeSeriesStore
        from asyncframework_tpu.utils.clock import ManualClock

        clk = ManualClock()
        store = TimeSeriesStore(capacity=512, clock=clk)
        rules = [r for r in parse_rules(AsyncConf().get(conf_mod.SLO_RULES))
                 if r.name == "shard_availability"]
        eng = SLOEngine(rules, store=store,
                        now_fn=lambda: clk.now_ms() / 1e3)

        def tick(dark: float, n: int):
            for _ in range(n):
                clk.advance(1000)
                store.record("ps_shards.dark_ranges", dark)
                eng.evaluate()

        tick(0.0, 20)
        assert eng.evaluate()["shard_availability"]["state"] == OK
        tick(1.0, 20)  # a range is dark past the burn window
        assert eng.evaluate()["shard_availability"]["state"] == FIRING
        tick(0.0, 20)  # recovered
        assert eng.evaluate()["shard_availability"]["state"] == OK

    def test_per_shard_metrics_labels_and_status_section(self):
        """A shard child's telemetry endpoint: every /metrics sample
        carries the shard label (per-shard series never collapse in an
        aggregator) and strict-parses; /api/status carries the shardgroup
        counter family and the SLO health section with the
        shard_availability rule."""
        import urllib.request

        from asyncframework_tpu.metrics.live import LiveUIServer
        from asyncframework_tpu.metrics.prom import parse_exposition

        sg._bump("shards_restarted")
        srv = LiveUIServer(None, port=0, role="ps-shard-1",
                           labels={"shard": "1"}).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            text = urllib.request.urlopen(f"{base}/metrics",
                                          timeout=5).read().decode()
            samples = parse_exposition(text)
            assert samples, "empty exposition"
            for (_name, labels) in samples:
                ld = dict(labels)
                assert ld.get("shard") == "1"
                assert ld.get("role") == "ps-shard-1"
            assert any(name == "async_shardgroup_shards_restarted_total"
                       for (name, _l) in samples)
            status = json.loads(urllib.request.urlopen(
                f"{base}/api/status", timeout=5).read())
            assert status["counters"]["shardgroup"].get(
                "shards_restarted") == 1
            assert "shard_availability" in status["health"]["rules"]
        finally:
            srv.stop()

    def test_registry_has_shardgroup_family(self):
        from asyncframework_tpu.metrics import registry, reset_totals

        assert "shardgroup" in registry.families()
        sg._bump("sharded_pulls")
        reset_totals()
        assert sg.shard_totals() == {}


# ------------------------------------------- restart-loop double-spawn fix
class TestRestartDoubleSpawnGuard:
    def test_concurrent_scans_relaunch_exactly_once(self, tmp_path,
                                                    monkeypatch):
        """ISSUE 13 satellite: a relaunch registers its pid/pstart
        under the supervisor lock the moment the child is Popen'd --
        BEFORE the (possibly long) announce wait -- and _restart
        re-checks the slot's membership state under the restart lock.
        Racing scans (check_once is public: the monitor, tests, and
        operators may overlap) therefore schedule exactly ONE relaunch:
        before the fix, a second scan queued behind the lock would kill
        the fresh child and spawn another.  The slow-exec stub widens
        the pre-announce window the race needs."""
        import threading

        cfg = make_cfg(num_workers=2, num_iterations=10**6)
        group = sg.ShardGroup(
            cfg, 8, 64, 1, checkpoint_dir=str(tmp_path),
            dead_after_s=1.0, check_interval_s=60.0,  # monitor parked
            stderr_dir=str(tmp_path),
        ).start()
        spawns = []
        real_popen = sg.subprocess.Popen

        def slow_popen(*a, **kw):
            spawns.append(time.monotonic())
            time.sleep(1.0)  # slow exec: the pre-announce window
            return real_popen(*a, **kw)

        try:
            os.kill(group.pid_of(0), signal.SIGKILL)
            deadline = time.monotonic() + 10.0
            while (group._procs[0].proc.poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            monkeypatch.setattr(sg.subprocess, "Popen", slow_popen)
            scans = [
                threading.Thread(target=group.check_once,
                                 name=f"race-scan-{i}", daemon=True)
                for i in range(3)
            ]
            for t in scans:
                t.start()
            for t in scans:
                t.join(timeout=120.0)
            assert not any(t.is_alive() for t in scans)
            assert len(spawns) == 1, \
                f"{len(spawns)} relaunches for one death"
            assert group.restarts_of(0) == 1
            # the one relaunched child is ALIVE (no second scan killed
            # it) and serving on its pinned port
            proc = group._procs[0].proc
            assert proc is not None and proc.poll() is None
            hdr = _probe_shardmap(group, 0, timeout_s=15.0)
            assert hdr["op"] == "SHARDMAP"
            # and a LATER scan with the child healthy spawns nothing
            group.check_once()
            assert len(spawns) == 1
        finally:
            group.stop()


def _probe_shardmap(group, index, timeout_s):
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        try:
            return sg._oneshot("127.0.0.1", group.port_of(index),
                               {"op": "SHARDMAP"}, timeout_s=2.0)
        except (ConnectionError, OSError) as e:
            last = e
            time.sleep(0.2)
    raise AssertionError(f"relaunched shard never served: {last}")


# --------------------------------------------- the acceptance: kill a shard
@pytest.mark.shard
class TestKillShardMidRun:
    """Real OS processes end to end: a 3-shard group under the controller,
    two worker processes, SIGKILL of a secondary shard mid-run."""

    NW, N, D = 8, 4096, 24
    ITERS = 500

    def _worker(self, port, wpid, tmp, eval_on=True):
        env = dict(os.environ)
        env.update({
            "PS_ROLE": "worker", "PS_PORT": str(port),
            "PS_WORKER_ID": str(wpid), "PS_NUM_WORKER_PROCS": "2",
            "PS_NUM_ITER": str(self.ITERS),
            "JAX_PLATFORMS": "cpu",
        })
        if not eval_on:
            env["PS_EVAL"] = "0"
        return subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(tmp, f"worker{wpid}.stderr.log"), "w"),
            text=True,
        )

    def test_sigkill_one_shard_of_three(self, tmp_path):
        # cfg MUST mirror tests/ps_dcn_child.py::config()
        cfg = SolverConfig(
            num_workers=self.NW, num_iterations=self.ITERS, gamma=1.2,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
            printer_freq=50, seed=42, calibration_iters=20,
            run_timeout_s=120.0,
        )
        group = sg.ShardGroup(
            cfg, self.D, self.N, 3, checkpoint_dir=str(tmp_path),
            worker_procs=2, dead_after_s=1.0, check_interval_s=0.2,
            stderr_dir=str(tmp_path),
        ).start()
        workers = []
        killed_pid = None
        try:
            port0 = group.port_of(0)
            workers = [self._worker(port0, 0, str(tmp_path)),
                       self._worker(port0, 1, str(tmp_path))]
            # watch shard 1's merge clock via lock-free SUBSCRIBE; kill
            # only after its cadence checkpoint exists (clock > 50) so
            # the restart actually exercises durable recovery.  The
            # threshold is chaos-seeded: each sweep seed kills at a
            # different point of the run.
            kill_after = 60 + (CHAOS_SEED % 50)
            watch = ps_dcn.PSClient("127.0.0.1", group.port_of(1))
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                got = watch.subscribe(0)
                if got is not None and got[2] >= kill_after:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("shard 1 never reached the kill threshold")
            try:
                watch.bye()
            except (ConnectionError, OSError):
                pass
            killed_pid = group.pid_of(1)
            os.kill(killed_pid, signal.SIGKILL)
            # the controller must detect the corpse and relaunch it from
            # its durable checkpoint on the SAME port
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if group.restarts_of(1) >= 1:
                    break
                time.sleep(0.1)
            assert group.restarts_of(1) >= 1, "shard 1 was never restarted"
            # the run must COMPLETE through the blip
            result0 = group.result_of(0, timeout_s=90.0)
            assert result0 is not None, "primary never finished"
            assert result0["done"] is True
            assert result0["accepted"] == self.ITERS
            # full coverage: every logical worker contributed
            assert set(map(int, result0["accepted_by_wid"])) == set(
                range(self.NW))
            # the end-of-run eval plane survived too: a real, decreasing
            # loss trajectory assembled across all three ranges
            traj = result0.get("trajectory")
            assert traj, "no trajectory (eval plane died with the shard?)"
            assert traj[-1][1] < traj[0][1] * 0.2, traj
            group.finish()
            # recovery observability: the restarted child announced what
            # it resumed from (the durable checkpoint's k), and the
            # controller counted the death + restart
            assert group._procs[1].resumed_from is not None, \
                "restarted shard did not resume from its checkpoint"
            totals = sg.shard_totals()
            assert totals.get("shard_deaths", 0) >= 1
            assert totals.get("shards_restarted", 0) >= 1
            # the controller's /api/status grows the per-shard section
            # (metrics/live.py reads the active group)
            from asyncframework_tpu.metrics.live import process_status

            section = process_status("test").get("shards")
            assert section is not None
            assert section["restarts"] >= 1
            assert set(section["members"]) == {"0", "1", "2"}
            # exactly-once across the restart: the recovered shard's
            # result line (its SECOND stdout line of this life) reports a
            # consistent clock -- every accepted push counted once, and
            # replays that were already applied+checkpointed were
            # answered from the RESTORED dedup window, not re-merged
            result1 = group.result_of(1, timeout_s=30.0)
            if result1 is not None:  # restarted life's lines shift by one
                assert result1.get("accepted", 0) + \
                    result1.get("dropped", 0) <= result1.get("clock", 0) + 1
            for w in workers:
                rc = w.wait(timeout=60.0)
                assert rc == 0, f"worker exited rc={rc}"
            out = [json.loads(w.stdout.read().splitlines()[-1])
                   for w in workers]
            assert sum(o["gradients"] for o in out) >= self.ITERS
        finally:
            for w in workers:
                if w.poll() is None:
                    w.kill()
            group.stop()

    @pytest.mark.soak
    def test_sigkill_primary_shard(self, tmp_path):
        """The primary (wave gate + eval plane) is a first-class member
        too: SIGKILL it mid-run, the controller relaunches it from its
        checkpoint on the same port, workers re-dial, the run completes."""
        cfg = SolverConfig(
            num_workers=self.NW, num_iterations=self.ITERS, gamma=1.2,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
            printer_freq=50, seed=42, calibration_iters=20,
            run_timeout_s=120.0,
        )
        group = sg.ShardGroup(
            cfg, self.D, self.N, 3, checkpoint_dir=str(tmp_path),
            worker_procs=2, dead_after_s=1.0, check_interval_s=0.2,
            stderr_dir=str(tmp_path),
        ).start()
        workers = []
        try:
            port0 = group.port_of(0)
            workers = [self._worker(port0, 0, str(tmp_path)),
                       self._worker(port0, 1, str(tmp_path))]
            watch = ps_dcn.PSClient("127.0.0.1", port0)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                got = watch.subscribe(0)
                if got is not None and got[2] >= 80:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("primary never reached the kill threshold")
            try:
                watch.bye()
            except (ConnectionError, OSError):
                pass
            os.kill(group.pid_of(0), signal.SIGKILL)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if group.restarts_of(0) >= 1:
                    break
                time.sleep(0.1)
            assert group.restarts_of(0) >= 1
            result0 = group.result_of(0, timeout_s=90.0)
            assert result0 is not None and result0["done"] is True
            assert result0["accepted"] == self.ITERS
            assert result0.get("resumed_from") is not None
            group.finish()
            for w in workers:
                assert w.wait(timeout=60.0) == 0
        finally:
            for w in workers:
                if w.poll() is None:
                    w.kill()
            group.stop()
