"""Auxiliary subsystem tests: pallas kernel, multihost helpers, HBM
planning, HTML report."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from asyncframework_tpu.metrics import (
    EventLogWriter,
    GradientMerged,
    JobEnd,
    JobStart,
    ModelSnapshot,
    TaskEnd,
    WorkerLost,
    render_report,
)
from asyncframework_tpu.ops.pallas_kernels import (
    fused_masked_grad,
    reference_masked_grad,
)
from asyncframework_tpu.parallel import multihost
from asyncframework_tpu.utils import hbm


class TestFusedMaskedGrad:
    """interpret=True: the Pallas kernel runs on the CPU interpreter here
    and compiles natively on TPU (same code path; bench covers that)."""

    @pytest.mark.parametrize("n,d", [(256, 128), (300, 100), (64, 17)])
    def test_matches_oracle(self, rng, n, d):
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = rng.normal(size=(n,)).astype(np.float32)
        w = rng.normal(size=(d,)).astype(np.float32)
        mask = (rng.random(n) < 0.5).astype(np.float32)
        got = fused_masked_grad(X, y, w, mask, interpret=True)
        want = reference_masked_grad(X, y, w, mask)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-3
        )

    def test_no_mask_means_all_rows(self, rng):
        X = rng.normal(size=(64, 32)).astype(np.float32)
        y = rng.normal(size=(64,)).astype(np.float32)
        w = rng.normal(size=(32,)).astype(np.float32)
        got = fused_masked_grad(X, y, w, interpret=True)
        want = reference_masked_grad(X, y, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-3)

    def test_row_tile_bigger_than_n(self, rng):
        X = rng.normal(size=(16, 8)).astype(np.float32)
        y = rng.normal(size=(16,)).astype(np.float32)
        w = rng.normal(size=(8,)).astype(np.float32)
        got = fused_masked_grad(X, y, w, row_tile=4096, interpret=True)
        want = reference_masked_grad(X, y, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-3)


class TestMultihost:
    def test_single_process_noop(self):
        assert multihost.ensure_initialized() is False
        assert not multihost.is_initialized()
        pid, count = multihost.process_info()
        assert pid == 0 and count == 1

    def test_sync_hosts_barrier_passes(self):
        multihost.sync_hosts()  # single host: psum over local devices

    def test_global_mesh_spans_devices(self):
        mesh = multihost.global_mesh()
        assert mesh.devices.size == jax.device_count()
        assert mesh.axis_names == ("dp",)


class TestHbmPlanning:
    def test_nbytes(self):
        assert hbm.nbytes((10, 10)) == 400
        assert hbm.nbytes((4,), np.float64) == 32

    def test_plan_fits_and_overflows(self):
        plan = hbm.plan_dataset(
            n=8_100_000, d=784, num_workers=8, num_devices=8,
            budget_bytes=16 * 1024**3,
        )
        assert plan.fits  # mnist8m sharded 8 ways: ~3.2 GB/device
        assert 0 < plan.utilization < 1
        plan.require_fits()

        too_big = hbm.plan_dataset(
            n=8_100_000, d=784, num_workers=1, num_devices=1,
            budget_bytes=16 * 1024**3,
        )
        assert not too_big.fits  # whole mnist8m on one device: ~25 GB
        with pytest.raises(MemoryError):
            too_big.require_fits()

    def test_history_table_and_versions_accounted(self):
        base = hbm.plan_dataset(1000, 10, 2, 2, budget_bytes=10**9)
        with_hist = hbm.plan_dataset(
            1000, 10, 2, 2, budget_bytes=10**9, history_table=True
        )
        assert with_hist.bytes_per_device > base.bytes_per_device

    def test_device_budget_queryable(self):
        assert hbm.device_hbm_bytes() > 0

    def test_fmt_bytes(self):
        assert hbm.fmt_bytes(512) == "512 B"
        assert hbm.fmt_bytes(2 * 1024**3) == "2.0 GiB"


class TestHtmlReport:
    def test_report_from_event_log(self, tmp_path):
        log = tmp_path / "events.jsonl"
        w = EventLogWriter(log)
        w.on_event(JobStart(0.0, job_id=1, worker_ids=(0, 1)))
        for i in range(20):
            w.on_event(GradientMerged(
                float(i), worker_id=i % 2, staleness=i % 3,
                accepted=i % 5 != 0, iteration=i,
            ))
            w.on_event(ModelSnapshot(float(i), iteration=i,
                                     objective=1.0 / (i + 1)))
        w.on_event(TaskEnd(5.0, job_id=1, worker_id=0, attempt=0,
                           run_ms=12.5, succeeded=True))
        w.on_event(TaskEnd(6.0, job_id=1, worker_id=1, attempt=0,
                           run_ms=20.0, succeeded=False, error="boom"))
        w.on_event(WorkerLost(7.0, worker_id=1, reason="heartbeat timeout"))
        w.on_event(JobEnd(8.0, job_id=1, succeeded=False, error="aborted"))
        w.close()

        out = tmp_path / "report.html"
        doc = render_report(log, out, title="test run")
        assert out.read_text() == doc
        assert "<h1>test run</h1>" in doc
        assert "gradients merged" in doc and "<td>20</td>" in doc
        assert "heartbeat timeout" in doc
        assert "<svg" in doc  # charts rendered
        assert "boom" in doc

    def test_empty_log(self, tmp_path):
        log = tmp_path / "empty.jsonl"
        log.write_text("")
        doc = render_report(log)
        assert "not enough data" in doc
