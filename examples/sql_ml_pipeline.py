"""End-to-end data pipeline: CSV -> SQL -> features -> forest -> metrics.

The round-trip a reference user would run as spark.read.csv + spark.sql +
MLlib: load a table, filter/derive columns in SQL, train a random forest,
and evaluate with the metrics library -- all on the device-resident columnar
frame and histogram trees.
"""

import sys

sys.path.insert(0, ".")

import numpy as np


def main(n: int = 2000, seed: int = 0, quiet: bool = False):
    from asyncframework_tpu.ml import MulticlassMetrics, RandomForest
    from asyncframework_tpu.sql import ColumnarFrame, sql

    rs = np.random.default_rng(seed)
    x1 = rs.normal(size=n).astype(np.float32)
    x2 = rs.normal(size=n).astype(np.float32)
    noise = rs.normal(scale=0.3, size=n).astype(np.float32)
    label = (x1 * 1.5 + x2 * x2 + noise > 1.0).astype(np.int32)
    frame = ColumnarFrame({"x1": x1, "x2": x2, "label": label})

    # relational prep in SQL: derived feature + predicate pushdown
    prepped = sql(
        "SELECT x1, x2, x1 * x2 AS x1x2, label FROM t WHERE x1 > -3",
        t=frame,
    )
    X = np.stack(
        [np.asarray(prepped[c]) for c in ("x1", "x2", "x1x2")], axis=1
    )
    y = np.asarray(prepped["label"])

    half = len(y) // 2
    model = RandomForest(num_trees=8, max_depth=5, seed=seed).fit(
        X[:half], y[:half]
    )
    pred = model.predict(X[half:])
    metrics = MulticlassMetrics(pred, y[half:])
    if not quiet:
        per_class = sql(
            "SELECT label, COUNT(*) AS n FROM t GROUP BY label ORDER BY label",
            t=prepped,
        )
        print("class counts:", dict(zip(
            np.asarray(per_class["label"]).tolist(),
            np.asarray(per_class["n"]).tolist(),
        )))
        print(f"holdout accuracy: {metrics.accuracy:.3f}")
    return metrics.accuracy


if __name__ == "__main__":
    main()
