"""Replayable log ingest: exactly-once-ish stream processing.

Direct-stream parity (DirectKafkaInputDStream semantics without a broker):
a producer appends events to a durable on-disk LogTopic; the consumer reads
offset ranges per interval and commits its offset only after the interval's
outputs ran.  Kill the pipeline mid-stream and restart it: committed
batches never replay, the in-flight one does.
"""

import tempfile

import numpy as np

from asyncframework_tpu.streaming import (
    DirectLogStream,
    LogTopic,
    StreamingContext,
)
from asyncframework_tpu.utils.clock import ManualClock


def main(n_events=600, per_batch=200):
    tmp = tempfile.mkdtemp(prefix="log-topic-")
    rs = np.random.default_rng(7)

    # producer side: durable appends (another process could do this)
    topic = LogTopic(tmp, segment_bytes=16 * 1024)
    topic.append_many([
        {"user": int(u), "amount": round(float(a), 2)}
        for u, a in zip(rs.integers(0, 50, n_events),
                        rs.gamma(2.0, 10.0, n_events))
    ])

    # consumer side: per-interval revenue, offsets committed after output
    ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
    revenue = []
    (
        DirectLogStream(ssc, tmp, group="billing", max_per_batch=per_batch)
        .map_batch(lambda evs: round(sum(e["amount"] for e in evs), 2))
        .foreach_batch(lambda t, total: revenue.append(total))
    )
    interval = 0
    while LogTopic(tmp).committed_offset("billing") < n_events:
        interval += 1
        ssc.generate_batch(interval * 100)

    # a RESTARTED consumer on the same group sees nothing left to replay
    ssc2 = StreamingContext(batch_interval_ms=100, clock=ManualClock())
    replayed = []
    DirectLogStream(ssc2, tmp, group="billing").foreach_batch(
        lambda t, b: replayed.append(b)
    )
    ssc2.generate_batch(100)
    return revenue, replayed


if __name__ == "__main__":
    rev, rep = main()
    print(f"per-interval revenue: {rev}")
    print(f"replayed after restart: {rep} (committed consumption)")
