"""SQL front door: DISTINCT, HAVING, and multi-way joins over device frames.

``SparkSession.sql`` parity on the TPU build: SQL text lowers onto the fused
Column DSL; aggregates run as device segment reductions, joins as host key
index + device gathers.
"""

import numpy as np

from asyncframework_tpu.sql.frame import ColumnarFrame
from asyncframework_tpu.sql.parser import SQLContext


def main(n=4000, n_users=50):
    rs = np.random.default_rng(7)
    ctx = SQLContext()
    ctx.register("events", ColumnarFrame({
        "user": rs.integers(0, n_users, n),
        "amount": rs.gamma(2.0, 10.0, n).astype(np.float32),
        "kind": np.array(["view", "click", "buy"])[rs.integers(0, 3, n)],
    }))
    ctx.register("users", ColumnarFrame({
        "user": np.arange(n_users),
        "tier": np.array(["free", "pro"])[rs.integers(0, 2, n_users)],
    }))

    kinds = ctx.sql("SELECT DISTINCT kind FROM events ORDER BY kind")
    print("event kinds:", list(np.asarray(kinds["kind"])))

    heavy = ctx.sql(
        "SELECT user, SUM(amount) AS total, COUNT(*) AS n "
        "FROM events GROUP BY user HAVING total > 500 "
        "ORDER BY total DESC LIMIT 5"
    )
    print("top spenders over 500:")
    for u, t, c in zip(
        np.asarray(heavy["user"]), np.asarray(heavy["total"]),
        np.asarray(heavy["n"]),
    ):
        print(f"  user {u:3d}  total {t:8.1f}  events {c:4.0f}")

    joined = ctx.sql(
        "SELECT kind, COUNT(*) AS n FROM events JOIN users ON user "
        "WHERE tier = 'pro' GROUP BY kind ORDER BY kind"
    )
    print("pro-tier events by kind:",
          dict(zip(np.asarray(joined["kind"]),
                   np.asarray(joined["n"]).astype(int))))

    # window function: each user's single largest purchase
    from asyncframework_tpu.sql.expressions import col

    ranked = ctx.sql(
        "SELECT user, amount, ROW_NUMBER() OVER "
        "(PARTITION BY user ORDER BY amount DESC) AS rk FROM events"
    )
    top = ranked.filter(col("rk") == 1)
    print(f"window fn: top purchase per user ({len(top)} rows, "
          f"max {float(np.asarray(top['amount']).max()):.1f})")

    # round-3 surface: CTE + CASE + scalar subquery + UDF + set op
    ctx.register_udf("short_tier", lambda t: str(t)[:1].upper())
    bands = ctx.sql(
        "WITH spend AS ("
        "  SELECT user, SUM(amount) AS total FROM events GROUP BY user"
        ") "
        "SELECT short_tier(tier) AS t, "
        "       CASE WHEN total > (SELECT AVG(total) FROM spend) "
        "            THEN 'above' ELSE 'below' END AS band, "
        "       total "
        "FROM spend JOIN users ON user"
    )
    above = ctx.sql(
        "SELECT user FROM (SELECT user, SUM(amount) AS total FROM events "
        "GROUP BY user) s WHERE total BETWEEN 400 AND 10000 "
        "UNION SELECT user FROM users WHERE tier LIKE 'p%'"
    )
    n_above = int(
        np.asarray(bands["band"], object).tolist().count("above")
    )
    print(f"CASE/subquery: {n_above} users above mean spend; "
          f"UNION of big-spenders and pro tier: {len(above)} users")
    return heavy


if __name__ == "__main__":
    main()
