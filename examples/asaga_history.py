"""ASAGA: variance-reduced async SGD with an HBM-resident history table.

SparkASAGAThread parity: each worker's slice of the per-sample gradient
history lives in its device memory; the updater commits accepted deltas and
maintains the running mean ``alpha_bar`` (SAGA's control variate).  With the
history, the step size can stay constant and the loss still converges.
"""

from asyncframework_tpu.data import make_regression
from asyncframework_tpu.solvers import ASAGA, SolverConfig


def main(n=20_000, d=64, iters=1_500):
    X, y, _ = make_regression(n, d, seed=7)
    cfg = SolverConfig(
        num_workers=8,
        num_iterations=iters,
        gamma=0.5,
        batch_rate=0.1,
        bucket_ratio=0.5,
        printer_freq=max(iters // 10, 1),
        calibration_iters=50,
    )
    res = ASAGA(X, y, cfg).run()
    print(f"final objective {res.final_objective:.6f} "
          f"(start {res.trajectory[0][1]:.4f})")
    alpha = res.extras["alpha"]
    nz = sum((a != 0).sum() for a in alpha.values())
    total = sum(a.size for a in alpha.values())
    print(f"history table: {nz}/{total} entries written across "
          f"{len(alpha)} worker slices")
    return res


if __name__ == "__main__":
    main()
