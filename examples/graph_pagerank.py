"""PageRank + connected components on the compiled Pregel substrate.

GraphX parity: the whole vertex-program loop is one jitted lax.while_loop;
message aggregation is a segment scatter-combine, not a shuffle.
"""

import numpy as np

from asyncframework_tpu.graph import Graph, connected_components, pagerank


def main(n=2_000, e=10_000, seed=3):
    rs = np.random.default_rng(seed)
    g = Graph(rs.integers(0, n, e), rs.integers(0, n, e), n)
    r = np.asarray(pagerank(g, alpha=0.85, num_iterations=30))
    top = np.argsort(r)[::-1][:5]
    print("top-5 vertices by pagerank:")
    for v in top:
        print(f"  vertex {v:5d}  rank {r[v]:.6f}  "
              f"in-degree {int(g.in_degrees()[v])}")
    cc = np.asarray(connected_components(g))
    print(f"components: {len(np.unique(cc))} (largest "
          f"{np.bincount(cc).max()} vertices)")
    return r, cc


if __name__ == "__main__":
    main()
