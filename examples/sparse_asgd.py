"""Asynchronous SGD on rcv1-class sparse data, never densified.

The reference's third benchmark dataset (rcv1_full.binary: 47,236 features,
~0.16% dense) cannot be densified (131 GB); this example runs the same
async recipe on a synthetic problem of that shape using padded-ELL shards
(gather residuals + scatter-add gradients, all static shapes).
"""

import sys

sys.path.insert(0, ".")


def main(n: int = 2048, d: int = 47_236, iters: int = 150,
         workers: int = 8, quiet: bool = False):
    import jax

    from asyncframework_tpu.data import (
        SparseShardedDataset,
        make_sparse_regression,
    )
    from asyncframework_tpu.solvers import ASGD, SolverConfig

    devices = jax.devices()[:workers] if len(jax.devices()) >= workers \
        else jax.devices()
    indptr, indices, values, y = make_sparse_regression(
        n, d, density=0.002, seed=7
    )
    ds = SparseShardedDataset(indptr, indices, values, y, d, workers, devices)
    cfg = SolverConfig(
        num_workers=workers,
        num_iterations=iters,
        gamma=0.5,
        batch_rate=0.2,
        bucket_ratio=0.5,
        printer_freq=max(iters // 5, 1),
        seed=42,
        calibration_iters=10,
    )
    res = ASGD(ds, None, cfg, devices=devices).run()
    if not quiet:
        first, last = res.trajectory[0][1], res.trajectory[-1][1]
        print(f"sparse {n}x{d} (0.2% dense): obj {first:.4f} -> {last:.4f} "
              f"in {res.accepted} updates ({res.updates_per_sec:.0f}/s)")
    return res


if __name__ == "__main__":
    main()
