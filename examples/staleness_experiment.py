"""The paper's core experiment: async vs sync under injected stragglers.

Reproduces the shape of ASYNC's Figures 3-4 (arXiv:1907.08526) on a small
planted problem: with straggler delay injected (the reference's
delay-intensity knob), synchronous SGD pays the straggler every round while
bounded-staleness ASGD keeps updating; an unbounded-tau run and a stale-read
(ASYNCbroadcast) run complete the comparison.
"""

import sys

sys.path.insert(0, ".")


def run_one(mode, X, y, devices, iters, coeff, taw=2**31 - 1,
            stale_offset=None):
    from asyncframework_tpu.solvers import ASGD, SolverConfig

    cfg = SolverConfig(
        num_workers=8, num_iterations=iters, gamma=0.5,
        taw=taw, batch_rate=0.3, bucket_ratio=0.5,
        printer_freq=max(iters // 10, 1), coeff=coeff, seed=42,
        calibration_iters=10, stale_read_offset=stale_offset,
    )
    solver = ASGD(X, y, cfg, devices=devices)
    res = solver.run_sync() if mode == "sync" else solver.run()
    return res


def main(n: int = 4096, d: int = 32, iters: int = 200, coeff: float = 2.0,
         quiet: bool = False):
    import jax

    from asyncframework_tpu.data import make_regression

    X, y, _ = make_regression(n, d, seed=3)
    devices = jax.devices()[:8] if len(jax.devices()) >= 8 else jax.devices()

    rows = []
    for name, kwargs in [
        ("sync + straggler", dict(mode="sync", coeff=coeff,
                                  iters=max(iters // 8, 10))),
        ("async tau=inf", dict(mode="async", coeff=coeff, iters=iters)),
        ("async tau=8", dict(mode="async", coeff=coeff, iters=iters, taw=8)),
        ("async stale-read-2", dict(mode="async", coeff=coeff, iters=iters,
                                    stale_offset=2)),
    ]:
        mode = kwargs.pop("mode")
        it = kwargs.pop("iters")
        res = run_one(mode, X, y, devices, it, **kwargs)
        rows.append((name, res))
        if not quiet:
            first, last = res.trajectory[0][1], res.trajectory[-1][1]
            print(f"{name:>20}: obj {first:8.4f} -> {last:8.6f}  "
                  f"updates/s={res.updates_per_sec:7.1f}  "
                  f"max_staleness={res.max_staleness}  "
                  f"dropped={res.dropped}")
    return {name: res for name, res in rows}


if __name__ == "__main__":
    main()
