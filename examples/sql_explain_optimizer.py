"""The SQL optimizer at work: EXPLAIN as the plan-shape window.

Round-5 catalyst-parity rewrites, each visible in the printed plan:

- join reordering (``ReorderJoin`` role): a badly written star query
  rebuilds with the 2-row dimension first;
- predicate pushdown THROUGH a window function when the filter touches
  only PARTITION BY keys;
- pruning + pushdown crossing UNION ALL into both lazy CSV readers;
- a twice-referenced CTE as an execute-once Shared node.
"""

import tempfile

import numpy as np

from asyncframework_tpu.sql import ColumnarFrame
from asyncframework_tpu.sql.parser import SQLContext


def main():
    rs = np.random.default_rng(3)
    ctx = SQLContext()
    n = 50_000
    ctx.register("fact_a", ColumnarFrame({
        "k": rs.integers(0, 100, n).astype(np.int32),
        "x": rs.normal(size=n).astype(np.float32),
    }))
    ctx.register("fact_b", ColumnarFrame({
        "k": rs.integers(0, 100, n).astype(np.int32),
        "y": rs.normal(size=n).astype(np.float32),
    }))
    ctx.register("dim", ColumnarFrame({
        "k": np.asarray([3, 7], np.int32),
        "label": np.asarray(["three", "seven"], object),
    }))

    print("== join reordering (facts written first, dim joins first) ==")
    q = "SELECT k, x, y, label FROM fact_a JOIN fact_b ON k JOIN dim ON k"
    for (line,) in ctx.sql("EXPLAIN " + q).collect():
        print(line)
    print(f"rows: {len(ctx.sql(q))}")

    print("\n== predicate sinks below the window (PARTITION BY key) ==")
    q = ("SELECT k, x, rn FROM (SELECT k, x, ROW_NUMBER() OVER "
         "(PARTITION BY k ORDER BY x DESC) AS rn FROM fact_a) "
         "WHERE k = 3")
    for (line,) in ctx.sql("EXPLAIN " + q).collect():
        print(line)

    print("\n== pruning + pushdown cross UNION ALL into lazy readers ==")
    with tempfile.NamedTemporaryFile(
        "w", suffix=".csv", delete=False
    ) as f1, tempfile.NamedTemporaryFile(
        "w", suffix=".csv", delete=False
    ) as f2:
        f1.write("a,b,unused\n1,10,0\n2,20,0\n")
        f2.write("a,b,unused\n3,30,0\n4,40,0\n")
    ctx.register_csv("t1", f1.name)
    ctx.register_csv("t2", f2.name)
    q = ("SELECT a FROM (SELECT * FROM t1 UNION ALL SELECT * FROM t2) "
         "WHERE a > 1")
    for (line,) in ctx.sql("EXPLAIN " + q).collect():
        print(line)
    print("result:", sorted(a for (a,) in ctx.sql(q).collect()))

    print("\n== twice-referenced CTE: one Shared body ==")
    q = ("WITH s AS (SELECT k, SUM(x) AS t FROM fact_a GROUP BY k) "
         "SELECT t FROM s WHERE t > 10 UNION ALL SELECT t FROM s "
         "WHERE t < 0 - 10")
    for (line,) in ctx.sql("EXPLAIN " + q).collect():
        print(line)


if __name__ == "__main__":
    main()
