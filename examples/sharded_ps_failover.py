"""Sharded parameter server surviving a SIGKILL, in miniature.

The ISSUE 8 capability as a runnable demo: range-partition the model
across a 3-shard PS group of REAL OS processes (parallel/shardgroup.py),
train ASGD against it from this process, SIGKILL one shard mid-run, and
watch the controller restart it from its durable checkpoint while the run
completes with full coverage -- "shard blipped, run continued" instead of
"PS died, run over".

Run:  JAX_PLATFORMS=cpu python examples/sharded_ps_failover.py
"""

import os
import signal
import tempfile
import time

from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel.shardgroup import ShardGroup, shard_totals
from asyncframework_tpu.solvers import SolverConfig
from asyncframework_tpu.utils.threads import guarded


def main(n=4096, d=24, workers=8, iters=500, shards=3):
    set_global_conf(AsyncConf())
    import jax

    cfg = SolverConfig(
        num_workers=workers, num_iterations=iters, gamma=1.2,
        taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5, printer_freq=50,
        seed=42, calibration_iters=20, run_timeout_s=120.0,
    )
    ds = ShardedDataset.generate_on_device(
        n, d, workers, devices=jax.devices()[:1], seed=11, noise=0.01)
    with tempfile.TemporaryDirectory() as ckpt_dir:
        group = ShardGroup(
            cfg, d, n, shards, checkpoint_dir=ckpt_dir, worker_procs=1,
            dead_after_s=1.0, check_interval_s=0.2,
        ).start()
        try:
            print(f"shard map: {group.smap}")

            import threading

            def kill_one_shard():
                # wait for the victim's cadence checkpoint, then kill -9
                watch = ps_dcn.PSClient("127.0.0.1", group.port_of(1))
                while True:
                    got = watch.subscribe(0)
                    if got is not None and got[2] >= 80:
                        break
                    time.sleep(0.02)
                pid = group.pid_of(1)
                print(f"SIGKILL shard 1 (pid {pid}) at clock {got[2]}")
                os.kill(pid, signal.SIGKILL)

            threading.Thread(target=guarded(kill_one_shard, "kill-shard"),
                             name="kill-one-shard", daemon=True).start()
            shards_data = {w: ds.shard(w) for w in range(workers)}
            ps_dcn.run_worker_process(
                "127.0.0.1", group.port_of(0), list(range(workers)),
                shards_data, cfg, d, n, eval_wid=0, deadline_s=120.0)
            group.finish()
            result = group.result_of(0, timeout_s=60.0)
            totals = shard_totals()
            print(f"run done        {result['done']}")
            print(f"accepted        {result['accepted']}/{iters}")
            print(f"coverage        {len(result['accepted_by_wid'])}"
                  f"/{workers} workers")
            print(f"shard deaths    {totals.get('shard_deaths', 0)}")
            print(f"shard restarts  {totals.get('shards_restarted', 0)}")
            print(f"shard 1 resumed from checkpoint k="
                  f"{group._procs[1].resumed_from}")
            traj = result.get("trajectory") or []
            if traj:
                print(f"loss            {traj[0][1]:.4f} -> "
                      f"{traj[-1][1]:.4f}")
            return result
        finally:
            group.stop()


if __name__ == "__main__":
    main()
