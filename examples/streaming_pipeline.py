"""Micro-batch streaming: windowed aggregation over a jitted pipeline.

DStream parity: batches flow through a lazy transform graph; each interval's
work is one XLA dispatch; a sliding window re-uses parent batches.
"""

import numpy as np

import jax
import jax.numpy as jnp

from asyncframework_tpu.streaming import StreamingContext
from asyncframework_tpu.utils.clock import ManualClock


def main(n_batches=8, batch=256, d=32):
    rs = np.random.default_rng(0)
    batches = [rs.normal(size=(batch, d)).astype(np.float32)
               for _ in range(n_batches)]
    featurize = jax.jit(lambda b: jnp.tanh(b) @ jnp.ones((d,)) / d)

    clock = ManualClock()
    ssc = StreamingContext(batch_interval_ms=100, clock=clock)
    out = []
    (
        ssc.queue_stream(batches)
        .map_batch(featurize)                     # jitted per-interval op
        .window(3)                                 # last 3 intervals
        .map_batch(lambda bs: float(jnp.concatenate(bs).mean()))
        .foreach_batch(lambda t, v: out.append((t, v)))
    )
    for k in range(1, n_batches + 1):              # deterministic ticks
        ssc.generate_batch(k * 100)
    for t, v in out:
        print(f"t={t:4d}ms  window-mean={v:+.5f}")
    return out


if __name__ == "__main__":
    main()
