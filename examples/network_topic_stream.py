"""Network-attached streaming source: a topic server process + remote
consumers over TCP.

Round-5 parity with the reference's direct Kafka stream
(``DirectKafkaInputDStream``): the broker role is a LogTopicServer
process serving durable topics over the framework's own DCN framing;
producers and consumers connect with ``RemoteLogTopic`` from anywhere.
Offsets live server-side and commit only after each interval's outputs,
so a consumer that dies and restarts — even on another host — resumes
exactly past its last completed batch.
"""

import tempfile

import numpy as np

from asyncframework_tpu.streaming import (
    DirectLogStream,
    LogTopicServer,
    RemoteLogTopic,
    StreamingContext,
)
from asyncframework_tpu.utils.clock import ManualClock


def main(n_events=500, per_batch=125):
    root = tempfile.mkdtemp(prefix="topic-srv-")
    # the "broker": in production `bin/async-topic-server --root ...` runs
    # this in its own process; in-process here so the example is one file
    srv = LogTopicServer(root)
    host, port = srv.start()

    # producer: a remote client (any process, any host)
    rs = np.random.default_rng(11)
    producer = RemoteLogTopic(host, port, "orders")
    producer.append_many([
        {"sku": int(s), "qty": int(q)}
        for s, q in zip(rs.integers(0, 20, n_events),
                        rs.integers(1, 5, n_events))
    ])

    # consumer 1: processes two intervals, then "crashes"
    ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
    seen = []
    (
        DirectLogStream(ssc, RemoteLogTopic(host, port, "orders"),
                        group="fulfillment", max_per_batch=per_batch)
        .map_batch(lambda evs: sum(e["qty"] for e in evs))
        .foreach_batch(lambda t, units: seen.append(units))
    )
    ssc.generate_batch(100)
    ssc.generate_batch(200)
    print(f"consumer 1 shipped {len(seen)} batches: {seen}")

    # consumer 2 (fresh state, same group): resumes at the SERVER-side
    # committed offset — nothing replays, nothing is skipped
    ssc2 = StreamingContext(batch_interval_ms=100, clock=ManualClock())
    seen2 = []
    (
        DirectLogStream(ssc2, RemoteLogTopic(host, port, "orders"),
                        group="fulfillment", max_per_batch=per_batch)
        .map_batch(lambda evs: sum(e["qty"] for e in evs))
        .foreach_batch(lambda t, units: seen2.append(units))
    )
    ssc2.generate_batch(100)
    ssc2.generate_batch(200)
    print(f"consumer 2 (restarted) shipped {len(seen2)} batches: {seen2}")

    committed = RemoteLogTopic(host, port, "orders").committed_offset(
        "fulfillment"
    )
    assert committed == n_events, committed
    assert len(seen) + len(seen2) == n_events // per_batch
    srv.stop()
    print(f"all {n_events} events consumed exactly once "
          f"(committed offset {committed})")


if __name__ == "__main__":
    main()
