"""Asynchronous bounded-staleness SGD with straggler injection + HTML report.

The SparkASGDThread experiment (reference README figure-3/4 recipes) in
miniature: 8 workers, tau-filtered updates, cloud-mode stragglers, and the
run report rendered from the event log.
"""

from asyncframework_tpu.data import make_regression
from asyncframework_tpu.solvers import ASGD, SolverConfig


def main(n=20_000, d=128, iters=2_000):
    X, y, _ = make_regression(n, d, seed=42)
    cfg = SolverConfig(
        num_workers=8,
        num_iterations=iters,
        gamma=1.0,
        taw=32,                # bounded staleness
        batch_rate=0.1,
        bucket_ratio=0.7,      # wait for 70% of the fleet
        coeff=-1.0,            # cloud-mode long-tail stragglers
        printer_freq=max(iters // 20, 1),
        calibration_iters=100,
    )
    res = ASGD(X, y, cfg).run()
    print(f"final objective   {res.final_objective:.6f}")
    print(f"accepted/dropped  {res.accepted}/{res.dropped}")
    print(f"updates/sec       {res.updates_per_sec:.0f}")
    print(f"max staleness     {res.max_staleness}")
    print("trajectory (ms, objective):")
    for t, obj in res.trajectory[:: max(len(res.trajectory) // 8, 1)]:
        print(f"  ({t:9.1f}, {obj:.6f})")
    return res


if __name__ == "__main__":
    main()
