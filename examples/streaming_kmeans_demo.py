"""Streaming k-means: online cluster tracking over a micro-batch stream.

``StreamingKMeans.trainOn``/``predictOn`` parity: the model updates from
every interval's batch with exponential forgetfulness, so when the data
distribution drifts the centers follow it; prediction uses the model as of
each interval.  Every batch update is one jitted one-hot-matmul kernel.
"""

import numpy as np

from asyncframework_tpu.ml import StreamingKMeans
from asyncframework_tpu.streaming import StreamingContext
from asyncframework_tpu.utils.clock import ManualClock


def main(n_batches=10, per_cluster=40, drift=3.0):
    rs = np.random.default_rng(0)
    # two clusters that drift rightward over time
    batches = []
    for t in range(n_batches):
        shift = drift * t / n_batches
        batches.append(np.concatenate([
            np.array([-4 + shift, 0.0])
            + 0.2 * rs.normal(size=(per_cluster, 2)),
            np.array([4 + shift, 0.0])
            + 0.2 * rs.normal(size=(per_cluster, 2)),
        ]).astype(np.float32))

    clock = ManualClock()
    ssc = StreamingContext(batch_interval_ms=100, clock=clock)
    stream = ssc.queue_stream(batches)

    model = StreamingKMeans(k=2, decay_factor=0.5, seed=1)
    model.set_initial_centers(
        np.array([[-1.0, 0.0], [1.0, 0.0]], np.float32)
    )
    model.train_on(stream)
    labels_seen = []
    model.predict_on(stream).foreach_batch(
        lambda t, lab: labels_seen.append((t, np.asarray(lab)))
    )

    for k in range(1, n_batches + 1):
        ssc.generate_batch(k * 100)
    centers = np.sort(model.centers[:, 0])
    print(f"final centers (x): {np.round(centers, 2).tolist()} "
          f"(drifted from [-4, 4] by ~{drift * (n_batches - 1) / n_batches:.1f})")
    return model, labels_seen


if __name__ == "__main__":
    main()
