"""Long-context attention over a sequence-sharded mesh.

Ring attention: each device holds T/P of the sequence; K/V blocks rotate
over ICI while a flash-style online softmax accumulates -- exact attention
with O((T/P)^2) peak memory.  Runs on however many devices are attached
(use XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
for an 8-device virtual mesh).
"""

import numpy as np

import jax

from asyncframework_tpu.parallel import (
    make_mesh,
    reference_attention,
    ring_attention,
)


def main(t=512, h=8, d=64):
    devs = jax.devices()
    p = len(devs)
    t = t - (t % p)
    mesh = make_mesh(p, axis_names=("sp",), devices=devs)
    rs = np.random.default_rng(0)
    q, k, v = (rs.normal(size=(1, t, h, d)).astype(np.float32)
               for _ in range(3))
    out = ring_attention(q, k, v, mesh, causal=True)
    want = reference_attention(q, k, v, causal=True)
    err = float(np.abs(np.asarray(out) - np.asarray(want)).max())
    print(f"ring attention over {p} device(s), seq {t}: "
          f"max |err| vs full attention = {err:.2e}")
    return out


if __name__ == "__main__":
    main()
