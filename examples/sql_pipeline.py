"""Columnar analytics: expressions, groupBy aggregation, and a join.

Spark SQL DataFrame parity: expression trees fuse into XLA kernels;
groupBy-agg is a device segment reduction; joins gather on device from a
host-built index.
"""

import numpy as np

from asyncframework_tpu.sql import ColumnarFrame, col, lit


def main(n=10_000, seed=5):
    rs = np.random.default_rng(seed)
    orders = ColumnarFrame({
        "region": rs.choice(["east", "west", "south"], n),
        "units": rs.integers(1, 20, n).astype(np.int32),
        "price": rs.uniform(0.5, 9.5, n).astype(np.float32),
    })
    managers = ColumnarFrame({
        "region": np.array(["east", "west", "south"]),
        "manager": np.array(["ada", "bob", "eve"]),
    })
    report = (
        orders
        .with_column("revenue", col("units") * col("price"))
        .filter(col("revenue") > lit(10.0))
        .groupby("region")
        .agg(orders=("revenue", "count"),
             revenue=("revenue", "sum"),
             avg_order=("revenue", "mean"))
        .join(managers, on="region")
        .sort("revenue", ascending=False)
    )
    for region, n_orders, rev, avg, mgr in report.collect():
        print(f"{region:6s} manager={mgr:4s} orders={n_orders:5d} "
              f"revenue={rev:10.2f} avg={avg:6.2f}")
    return report


if __name__ == "__main__":
    main()
